"""Megabatched mission step: one dispatch chain per tick for N tenants.

Every per-mission ingredient is already deterministic, seeded and
config-driven, and the whole fleet tick is pure jax — so independent
missions lift onto a leading TENANT axis with `jax.vmap`. What does
NOT survive a naive ``vmap(fleet_step)`` is the loop-closure
``lax.cond``: under vmap a cond with a batched predicate lowers to
``select`` — BOTH branches execute, every tick, for every tenant, and
the rare-tick closure repair (full ring re-fusion + per-robot chain
verification + graph optimisation) becomes an every-tick tax that
erases the batching win. ``megabatch_step`` therefore hoists closure
handling out of the batch entirely: the jitted step advances every
tenant down the (common) NO-closure path — sense/match/fuse vmapped,
graph growth per-lane under ``lax.map`` — and reports per-tenant
closure-PENDING flags; ``megabatch_tick`` (the host-driven tick) then
re-runs each pending tenant's tick through the solo `fleet_step`
executable itself. That host hop is what makes closure ticks
bit-exact: XLA:CPU gives no cross-executable bit-stability (a
closure body recompiled inside the megabatch — vmapped OR
lax.map-wrapped — drifts 1e-11..1e-7 from the solo trace via
fusion/FMA and GEMM/Cholesky lowering differences, measured), so the
only airtight closure path IS the solo executable.

Bit-identity contract: a tenant's trajectory inside a megabatch equals
its solo `fleet_step` trajectory bit-for-bit — same seed, any bucket
on the EXACT ladder, any co-tenants (property-tested in
tests/test_tenancy.py). The ladder boundary is a backend fact (see
`EXACT_BUCKETS`): past it, XLA:CPU vectorizes the batched executable
with FMA/SIMD choices the solo executable's lowering does not make,
and NO construction reproduces solo bits — vmap, lax.map-wrapped
solo bodies, and separately-jitted sub-programs were all measured to
drift (1e-11..1e-7 per op). `bit_exact_buckets=False` opts into the
full bucket set at any size for throughput work (the bench's 32-way
megabatch), documented ulp-faithful rather than bit-exact there.

Bucketing: the tenant dimension is padded to the bucket set
{2^k} ∪ {3·2^(k-1)} (the PR 6 crop-span / PR 11 scan-batch idiom —
the 1.5x midpoints halve worst-case pad waste while the set stays
logarithmic) — restricted to `EXACT_BUCKETS` while the bit-exact
contract is armed — so admit/evict churn cannot explode
compiled-variant counts; the per-bucket variant budget is pinned in
`analysis/compile_budget.json`. Pad slots carry a copy of lane 0's
state with ``active=False`` and are frozen by a final select — an
exact no-op: a pad lane's state never advances, and vmap lanes are
independent, so pads cannot perturb active tenants.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig, ensure_valid_mode
from jax_mapping.models import fleet as FM

Array = jax.Array


#: The bit-exact tenant ladder: the subset of the {2^k} ∪ {3·2^(k-1)}
#: bucket set whose vmapped lowering is VERIFIED bit-identical to the
#: solo `fleet_step` executable for the shipped micro mission shape on
#: the XLA:CPU builder (the property suite pins it). The boundary is a
#: backend fact, not a design choice: at power-of-two batch sizes
#: >= 4 and at any size >= 16, LLVM vectorizes the tiny per-robot
#: arithmetic clusters (odometry rk2, matcher fine-stage pose
#: assembly) with FMA/SIMD contraction the solo executable's scalar
#: lowering does not use — measured est drift ~3e-10/step at
#: B ∈ {4, 8, 16, 17, 24, 32, 33}, bit-exact at B ∈ {2, 3, 5, 6, 9,
#: 12}. GOTCHA: the boundary also moves with compile-context knobs —
#: the test harness's `--xla_force_host_platform_device_count=8`
#: virtual mesh shifts LLVM's vectorization thresholds enough to
#: perturb edge-heavy configs even at B=2, which is why the
#: solo-parity gates run in CLEAN subprocesses (no mesh flag) and why
#: this ladder must be re-derived per backend/toolchain (TPU's
#: lanewise VPU lowering is a different story entirely — unmeasured
#: here).
EXACT_BUCKETS = (1, 2, 3, 6, 12)


def windowed_mission_config(cfg: SlamConfig) -> SlamConfig:
    """The per-tenant BOUNDED-MEMORY mission config: when
    `cfg.world.windowed`, every lane's device grid is the robocentric
    window (`window_tiles * serving.tile_cells` square, the same
    derivation the bridge mapper runs — ONE definition in
    world/store.window_slam_config), not the full logical extent. N
    tenants then cost N x window² device cells instead of N x
    logical² — the tenant axis is exactly where full-extent lane
    grids explode first (a 64-tenant megabatch at the production 4096²
    logical grid is 4 TB of lane grids; at an 8-tile window it is
    ~17 GB). Tenant lanes anchor their window at mission start and do
    NOT shift (megabatched missions are short-horizon; the shifting
    robocentric store is the bridge mapper's tier) — the window IS the
    mission's world extent. Identity when not windowed: bit-exact
    pre-PR lane shapes, the knob-off doctrine."""
    if not cfg.world.windowed:
        return cfg
    from jax_mapping.world.store import window_slam_config
    return window_slam_config(cfg)


def bucket_capacity(n: int, cap: Optional[int] = None,
                    exact: bool = True) -> int:
    """Smallest allowed tenant capacity >= n. `exact=True` (the
    default, `TenancyConfig.bit_exact_buckets`) picks from
    `EXACT_BUCKETS` — every capacity whose megabatch is bit-identical
    to solo runs on this backend — and refuses tenant counts past the
    ladder's top instead of silently degrading the contract.
    `exact=False` serves the full {2^k} ∪ {3·2^(k-1)} set to any
    size: trajectories are then ulp-faithful but NOT bit-exact on
    XLA:CPU past the exact ladder (see EXACT_BUCKETS). `cap` bounds
    the answer (the control plane's max_tenants)."""
    if n < 1:
        raise ValueError(f"bucket_capacity needs n >= 1, got {n}")
    if exact:
        for b in EXACT_BUCKETS:
            if b >= n:
                break
        else:
            raise ValueError(
                f"{n} tenant(s) exceed the bit-exact bucket ladder "
                f"(top {EXACT_BUCKETS[-1]} on this backend); set "
                "TenancyConfig.bit_exact_buckets=False for larger "
                "megabatches (ulp-faithful, not bit-exact, on "
                "XLA:CPU)")
    else:
        # ONE definition of the {2^k} ∪ {3·2^(k-1)} set repo-wide (the
        # PR 6 crop-span / PR 11 scan-batch helper) — the tenant axis
        # must not grow a drifting copy of it.
        from jax_mapping.ops.grid import _batch_bucket
        b = _batch_bucket(n)
    if cap is not None and b > cap:
        raise ValueError(f"{n} tenant(s) exceed max capacity {cap}")
    return b


class TenantBatch(NamedTuple):
    """Independent mission states stacked along a leading tenant axis.

    Every leaf of `states` (and `worlds` / `keys` / `active`) carries
    the same bucket-padded leading dimension B. `keys` is the
    per-mission PRNG identity (the seed the mission's `FleetState` was
    initialised from — restart/determinism bookkeeping, not consumed
    by the step itself: the fleet tick draws no randomness).
    """

    states: FM.FleetState    # every leaf (B, ...)
    worlds: Array            # (B, H, W) per-tenant ground truth
    keys: Array              # (B, 2) uint32 per-mission PRNG keys
    active: Array            # (B,) bool; pad/suspended slots False


def stack_states(states: Sequence[FM.FleetState]) -> FM.FleetState:
    """Stack per-mission FleetStates along a new leading tenant axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def make_tenant_batch(states: Sequence[FM.FleetState],
                      worlds: Sequence[Array],
                      keys: Sequence[Array],
                      capacity: Optional[int] = None,
                      exact: bool = True) -> TenantBatch:
    """Bucket-pad N missions into a TenantBatch. Pad slots duplicate
    lane 0 (identical shapes, no special-cased compute path) and are
    marked inactive — `megabatch_step`'s final select freezes them, so
    a pad tick is an exact no-op on state."""
    n = len(states)
    if n == 0:
        raise ValueError("make_tenant_batch needs at least one mission")
    if not (len(worlds) == len(keys) == n):
        raise ValueError("states / worlds / keys length mismatch")
    b = capacity if capacity is not None else bucket_capacity(
        n, exact=exact)
    if b < n:
        raise ValueError(f"capacity {b} < {n} tenant(s)")
    idx = list(range(n)) + [0] * (b - n)
    stacked = stack_states([states[i] for i in idx])
    return TenantBatch(
        states=stacked,
        worlds=jnp.stack([jnp.asarray(worlds[i]) for i in idx]),
        keys=jnp.stack([jnp.asarray(keys[i]) for i in idx]),
        active=jnp.asarray([i < n for i in range(b)]))


#: Lane-health word bits (uint32), computed on device inside the
#: megabatch dispatch when `TenancyConfig.lane_health` is armed and
#: re-derived host-side (`lane_health_host`) for closure-pending lanes
#: and re-admission probes — the same predicate either way.
HEALTH_NONFINITE = 1    # NaN/Inf in the lane's pose / grid-delta leaves
HEALTH_POSE_JUMP = 2    # within-step est translation > pose_jump_max_m
HEALTH_MATCH_FLOOR = 4  # accepted key match response < match_floor


def _health_word(cfg: SlamConfig, batch: TenantBatch,
                 states2: FM.FleetState, diag: FM.FleetDiag) -> Array:
    """The (B,) uint32 per-tenant health word, traced INSIDE the
    megabatch jit (cfg is static: knob-off traces a constant zeros
    output — identical lane numerics, zero extra dispatches either
    way). Reads the PRE-freeze `states2` so a flagged pad/inactive
    lane cannot occur (inactive lanes mask to 0 at the end)."""
    t = cfg.tenancy
    if not (t.enabled and t.lane_health):
        return jnp.zeros(batch.active.shape, jnp.uint32)
    # bit 0: NaN/Inf anywhere in the pose or grid-delta leaves. The
    # grid DELTA (not the grid) is what the lane produced this tick;
    # subtracting the held input also catches a lane whose input was
    # already poisoned (NaN - NaN = NaN).
    pose_ok = jnp.isfinite(states2.est_poses).reshape(
        states2.est_poses.shape[0], -1).all(axis=1)
    gd = states2.grid - batch.states.grid
    grid_ok = jnp.isfinite(gd).reshape(gd.shape[0], -1).all(axis=1)
    word = jnp.where(pose_ok & grid_ok, jnp.uint32(0),
                     jnp.uint32(HEALTH_NONFINITE))
    # bit 1: pose-jump magnitude — the max over robots of the
    # within-step estimated translation. NaN poses compare False here
    # (bit 0 already owns that failure mode).
    dxy = (states2.est_poses - batch.states.est_poses)[..., :2]
    jump = jnp.sqrt((dxy * dxy).sum(axis=-1)).max(axis=-1)
    word = word | jnp.where(jump > t.pose_jump_max_m,
                            jnp.uint32(HEALTH_POSE_JUMP), jnp.uint32(0))
    # bit 2: match-score floor, charged only where a key-step match
    # actually ran (sub-gate steps carry no match information).
    if t.match_floor > 0.0:
        low = (diag.match_response < t.match_floor) & diag.is_key
        word = word | jnp.where(
            low.reshape(low.shape[0], -1).any(axis=1),
            jnp.uint32(HEALTH_MATCH_FLOOR), jnp.uint32(0))
    return jnp.where(batch.active, word, jnp.uint32(0))


def lane_health_host(cfg: SlamConfig, old_state: FM.FleetState,
                     new_state: FM.FleetState,
                     diag=None) -> int:
    """Host-side twin of the device health word, over ONE lane (no
    tenant axis): used for closure-pending lanes (whose megabatch
    health described the discarded no-closure evolution) and for the
    re-admission probe's solo-tick verdict. Same predicate, numpy."""
    import numpy as np

    t = cfg.tenancy
    word = 0
    new_p = np.asarray(new_state.est_poses)
    old_p = np.asarray(old_state.est_poses)
    gd = np.asarray(new_state.grid) - np.asarray(old_state.grid)
    if not (np.isfinite(new_p).all() and np.isfinite(gd).all()):
        word |= HEALTH_NONFINITE
    dxy = (new_p - old_p)[..., :2]
    jump = np.sqrt((dxy * dxy).sum(axis=-1)).max()
    if jump > t.pose_jump_max_m:
        word |= HEALTH_POSE_JUMP
    if t.match_floor > 0.0 and diag is not None:
        low = (np.asarray(diag.match_response) < t.match_floor) \
            & np.asarray(diag.is_key)
        if low.any():
            word |= HEALTH_MATCH_FLOOR
    return word


@functools.partial(jax.jit, static_argnums=(0, 2))
def megabatch_step(cfg: SlamConfig, batch: TenantBatch,
                   world_res_m: float
                   ) -> tuple[TenantBatch, FM.FleetDiag, Array, Array]:
    """One megabatched NO-CLOSURE tick + per-tenant closure-pending
    flags + per-tenant health word: every active tenant advances
    exactly as its solo `fleet_step` would on a tick whose closure
    cond stays false (bit-for-bit), inactive slots are frozen, and the
    whole batch costs ONE dispatch chain. Returns ``(batch', diag,
    pending, health)`` where ``pending[i]`` means tenant i had a
    loop-closure candidate this tick — its lane in ``batch'`` is the
    (wrong) no-closure evolution and MUST be resolved by the caller;
    `megabatch_tick` is the host-driven form that does so through the
    solo `fleet_step` executable itself. ``health`` is the (B,) uint32
    lane-health word (HEALTH_* bits), computed in the SAME dispatch
    when `TenancyConfig.lane_health` is armed and constant zeros
    otherwise — arming it changes no lane numerics (the word is a pure
    reader of the tick's outputs) and adds no dispatch (cfg is static,
    so the reduction fuses into this very executable).

    Why closures resolve on the host: XLA:CPU gives no cross-
    executable bit-stability — the closure body's Gauss-Newton
    GEMM/Cholesky (and, under the test harness's virtual multi-device
    mesh, even a `lax.map`-wrapped copy of the solo graph) lowers with
    different fusion/FMA choices inside the megabatch executable and
    drifts 1e-11..1e-7 from the solo trace. The ONLY airtight way to
    keep a closure tick bit-identical to the solo run is to run it
    through the very same compiled `fleet_step` the solo run uses.
    Closure ticks are rare (the whole point of gating on candidates),
    so the per-tenant solo re-dispatch is the cold path.

    The returned FleetDiag carries the leading tenant axis; inactive
    lanes' diag rows are meaningless (their state did not advance)."""
    ensure_valid_mode(cfg)
    # Sense/policy/move/match/fuse: vmapped — bit-stable per lane on
    # the exact bucket ladder (EXACT_BUCKETS; past it the batched
    # vectorization departs from the solo lowering and the contract
    # is ulp-faithful only). Graph growth: per-lane lax.map — its
    # pose_between edge arithmetic fuses with different FMA choices
    # under a tenant vmap even at ladder buckets (measured ~1e-9 edge
    # drift at B=2 in edge-heavy missions), and the lax.map body's
    # (R,)-shaped fusion cluster lowers like the solo one.
    sense = jax.vmap(
        lambda s, w: FM._tick_sense(cfg, s, world_res_m, w))(
            batch.states, batch.worlds)
    (graphs, rings, k_idx, cand, attempt, xrobot, xcand,
     xattempt) = jax.lax.map(
        lambda a: FM._tick_graph(cfg, *a),
        (batch.states.graphs, batch.states.scan_rings, sense.est,
         sense.is_key, sense.scans, sense.res.accepted))
    pre = FM._TickPre(sim2=sense.sim2, pol=sense.pol, fr=sense.fr,
                      match_response=sense.res.response,
                      est=sense.est, is_key=sense.is_key,
                      grid=sense.grid, graphs=graphs, rings=rings,
                      k_idx=k_idx, scans=sense.scans, cand=cand,
                      attempt=attempt, xrobot=xrobot, xcand=xcand,
                      xattempt=xattempt)

    pending = (attempt | xattempt).any(axis=-1) & batch.active
    closed = jnp.zeros_like(pre.is_key)
    states2, diag = jax.vmap(
        lambda st, pr, g, gr, e, cl: FM._tick_finish(
            cfg, st, pr, g, gr, e, cl))(
                batch.states, pre, pre.grid, pre.graphs, pre.est,
                closed)

    # Health word BEFORE the freeze: it reads what each lane PRODUCED
    # this tick (inactive lanes mask to zero inside _health_word).
    health = _health_word(cfg, batch, states2, diag)

    # Freeze pad/suspended lanes: active lanes pass through untouched
    # (a True select is the identity), inactive lanes keep their
    # previous state bit-for-bit — the exact-no-op pad contract.
    def freeze(new, old):
        act = batch.active.reshape(
            (-1,) + (1,) * (new.ndim - 1))
        return jnp.where(act, new, old)

    states2 = jax.tree.map(freeze, states2, batch.states)
    return batch._replace(states=states2), diag, pending, health


def megabatch_tick(cfg: SlamConfig, batch: TenantBatch,
                   world_res_m: float
                   ) -> tuple[TenantBatch, FM.FleetDiag, "np.ndarray"]:
    """ONE host-driven megabatch tick, closure ticks included: the
    megabatch dispatch advances every tenant down the no-closure path
    and reports closure-pending lanes; each pending tenant's tick is
    then re-run from its PRE-tick lane state through the solo
    `fleet_step` — the identical executable the solo oracle runs, so
    closure ticks are bit-exact by construction — and written back
    into the lane (state AND diag row). The pending fetch doubles as
    the tick's device barrier; the health word rides the SAME barrier
    (the only host sync the tick pays). Returns ``(batch, diag,
    health)`` with ``health`` a host (B,) uint32 array — all zeros
    unless `TenancyConfig.lane_health` is armed. A closure-resolved
    lane's word is re-derived host-side from the solo outputs (its
    device word described the discarded no-closure evolution)."""
    import numpy as np

    new_batch, diag, pending, health = megabatch_step(
        cfg, batch, world_res_m)
    pending_np = np.asarray(pending)
    health_np = np.asarray(health).copy()
    lane_armed = cfg.tenancy.enabled and cfg.tenancy.lane_health
    if pending_np.any():
        states = new_batch.states
        for i in np.nonzero(pending_np)[0]:
            i = int(i)
            before = lane_state(batch, i)
            s1, d1 = FM.fleet_step(cfg, before, world_res_m,
                                   batch.worlds[i])
            states = jax.tree.map(lambda b, s: b.at[i].set(s),
                                  states, s1)
            diag = jax.tree.map(lambda b, s: b.at[i].set(s), diag, d1)
            if lane_armed:
                health_np[i] = lane_health_host(cfg, before, s1, d1)
        new_batch = new_batch._replace(states=states)
    return new_batch, diag, health_np


def lane_state(batch: TenantBatch, i: int) -> FM.FleetState:
    """Extract tenant lane `i`'s FleetState (device slices)."""
    return jax.tree.map(lambda x: x[i], batch.states)
