"""Rendezvous fleet merges: two worlds become one.

Two independently-launched fleets share no map, no pose frame, and no
knowledge of their relative origin — the deployment reality ISSUE 8
names (fleets start separately and must merge). This module detects
inter-fleet overlap and merges the fleets' worlds:

1. **Detection** — fleet B's freshest key scan is matched against fleet
   A's live shared grid through the same wide-window machinery loop
   closure and the relocalizer use (`relocalize_match`), seeded at
   fleet A's robot and graph poses (the cross-robot sweep idiom: the
   true pose, if the fleets overlap at all, lies in A's explored
   region). One accepted match is a basin, not an anchor — corridor
   aliases look legitimate — so acceptance is STREAK-verified: the
   implied inter-fleet transform must agree across
   `consecutive` consecutive attempts within the consistency radii
   (the Relocalizer's verification doctrine applied to a TRANSFORM
   instead of a pose).

2. **Alignment** — the verified match fixes the rigid SE(2) transform
   T with `T ⊕ pose_B = pose_A`; every B state (current pose, key
   chain, graph poses) maps through T.

3. **Merge** — one re-fusion at aligned poses: B's key-scan rings fuse
   into A's live grid at their transformed graph poses
   (`ops/grid.fuse_scans_masked`, the closure-repair idiom), the
   matched robot's graph gets an `ops/posegraph.anchor_tip` edge at the
   verified pose + one optimize pass, and the merged state list spans
   both fleets aliasing ONE shared grid — frontier assignment and
   FleetHealth (`absorb`) take the joined robots from there.

Host-orchestrated cold path, deterministic: no RNG anywhere, so two
same-seed missions merge at the same step with the same transform.
"""

from __future__ import annotations

import math
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from jax_mapping.config import SlamConfig
from jax_mapping.ops import frontier as F
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.recovery.relocalize import relocalize_match
from jax_mapping.utils import global_metrics as GM


# ---------------------------------------------------------------------------
# Host-side SE(2) (the merge is a cold path; numpy keeps it debuggable)
# ---------------------------------------------------------------------------

def _wrap(a):
    return (a + math.pi) % (2.0 * math.pi) - math.pi


def se2_apply(T: np.ndarray, poses: np.ndarray) -> np.ndarray:
    """Apply transform T (3,) to poses (..., 3): frame-B coordinates
    become frame-A coordinates."""
    T = np.asarray(T, np.float32)
    p = np.asarray(poses, np.float32)
    c, s = math.cos(float(T[2])), math.sin(float(T[2]))
    out = np.empty_like(p)
    out[..., 0] = T[0] + c * p[..., 0] - s * p[..., 1]
    out[..., 1] = T[1] + s * p[..., 0] + c * p[..., 1]
    out[..., 2] = _wrap(p[..., 2] + T[2])
    return out


def se2_from_pair(pose_a: np.ndarray, pose_b: np.ndarray) -> np.ndarray:
    """The rigid T with `se2_apply(T, pose_b) == pose_a` — the
    inter-fleet transform implied by one verified match (pose_a = the
    matched pose in A's frame, pose_b = the same physical pose in B's
    belief frame)."""
    a = np.asarray(pose_a, np.float64)
    b = np.asarray(pose_b, np.float64)
    th = _wrap(float(a[2]) - float(b[2]))
    c, s = math.cos(th), math.sin(th)
    tx = float(a[0]) - (c * float(b[0]) - s * float(b[1]))
    ty = float(a[1]) - (s * float(b[0]) + c * float(b[1]))
    return np.asarray([tx, ty, th], np.float32)


def transform_state(st, T: np.ndarray):
    """One SlamState expressed in frame A: current pose, key anchor and
    the whole graph map through T. The grid field is untouched — the
    caller re-fuses into the merged grid (aliasing is the mapper's
    job)."""
    import jax.numpy as jnp
    pose = jnp.asarray(se2_apply(T, np.asarray(st.pose, np.float32)))
    lkp = jnp.asarray(se2_apply(T, np.asarray(st.last_key_pose,
                                              np.float32)))
    gposes = jnp.asarray(se2_apply(T, np.asarray(st.graph.poses,
                                                 np.float32)))
    return st._replace(pose=pose, last_key_pose=lkp,
                       graph=st.graph._replace(poses=gposes))


def merge_fleets(cfg: SlamConfig, states_a: Sequence, states_b: Sequence,
                 T: np.ndarray,
                 anchor: Optional[Tuple[int, np.ndarray]] = None):
    """One shared world from two fleets: returns (merged_grid,
    merged_states) with B's states transformed by T, the matched B
    robot's graph anchored (+optimized) at the verified pose, and B's
    key-scan rings re-fused into A's live grid at the aligned poses.
    Every returned state aliases the merged grid — the shared-map
    contract a post-merge mapper expects."""
    moved = [transform_state(st, T) for st in states_b]
    if anchor is not None:
        j, verified_pose = anchor
        g2 = PG.anchor_tip(moved[j].graph, verified_pose)
        g2 = PG.optimize(cfg.loop, g2)
        moved[j] = moved[j]._replace(graph=g2)
    cap = cfg.loop.max_poses
    grid = states_a[0].grid
    for st in moved:
        grid = G.fuse_scans_masked(
            cfg.grid, cfg.scan, grid, st.scan_ring,
            st.graph.poses[:cap], st.graph.pose_valid[:cap])
    merged = [st._replace(grid=grid) for st in list(states_a) + moved]
    return grid, merged


def merged_frontier_assignment(cfg: SlamConfig, grid, states):
    """Frontier auction over the MERGED fleet: one compute_frontiers
    on the shared grid with every robot's pose — the joined robots
    compete for frontiers like they always belonged."""
    import jax.numpy as jnp
    poses = jnp.stack([st.pose for st in states])
    return F.compute_frontiers(cfg.frontier, cfg.grid, grid, poses)


# ---------------------------------------------------------------------------
# Detection: the cross-fleet overlap watcher
# ---------------------------------------------------------------------------

class RendezvousMerger:
    """Watches two live mappers for inter-fleet overlap; merges on a
    verified streak.

    Call `poll()` from the thread driving both stacks (the deterministic
    `run_steps` driver — the same clocking contract as FaultPlan): each
    poll costs one wide-match sweep; `every_n_polls` thins that to a
    cadence. After `merged` flips True, `merged_grid`/`merged_states`/
    `transform` hold the shared world. `_lock` guards the streak and
    the published result for HTTP-style readers; the sweep itself runs
    outside it (no device work under a lock)."""

    def __init__(self, cfg: SlamConfig, mapper_a, mapper_b,
                 min_response: float = 0.35, consecutive: int = 2,
                 consistency_m: float = 0.3,
                 consistency_rad: float = 0.3, max_seeds: int = 8,
                 min_keyscans: int = 3):
        self.cfg = cfg
        self.mapper_a = mapper_a
        self.mapper_b = mapper_b
        self.min_response = min_response
        self.consecutive = consecutive
        self.consistency_m = consistency_m
        self.consistency_rad = consistency_rad
        self.max_seeds = max_seeds
        self.min_keyscans = min_keyscans
        self._lock = threading.Lock()
        #: Verified-streak of implied transforms (3,) float32.
        self._streak: List[np.ndarray] = []
        self.transform: Optional[np.ndarray] = None
        self.merged_grid = None
        self.merged_states: Optional[List] = None
        self.n_attempts = 0
        self.n_accepted = 0
        self.merged = False

    # -- sweep ingredients ---------------------------------------------------

    def _seeds(self) -> np.ndarray:
        """Candidate poses in A's frame the wide match sweeps from:
        every A robot's live pose plus an even subsample of A's valid
        graph poses (the explored region's skeleton), capped at
        `max_seeds`."""
        seeds = [np.asarray(st.pose, np.float32)
                 for st in self.mapper_a.states]
        for i in range(self.mapper_a.n_robots):
            _gen, poses, valid, n, _k = self.mapper_a.graph_snapshot(i)
            idx = np.nonzero(valid[:n])[0]
            if len(idx):
                take = max(1, len(idx) // max(1, self.max_seeds))
                seeds.extend(poses[idx[::take]])
        seeds = np.asarray(seeds, np.float32).reshape(-1, 3)
        return seeds[:self.max_seeds]

    def _probe(self):
        """Fleet B's freshest verified key (scan, pose-in-B) pair, or
        None before enough chain exists: the ring slot AT the graph tip
        — the scan was recorded at exactly that pose."""
        best = None
        for j in range(self.mapper_b.n_robots):
            st = self.mapper_b.states[j]
            n = int(st.graph.n_poses)
            if n >= self.min_keyscans and (best is None or n > best[0]):
                best = (n, j, st)
        if best is None:
            return None
        n, j, st = best
        ranges = np.asarray(st.scan_ring[n - 1], np.float32)
        pose_b = np.asarray(st.graph.poses[n - 1], np.float32)
        if not ranges.any():
            return None                  # empty ring slot (padding)
        return j, ranges, pose_b

    # -- the per-cadence attempt --------------------------------------------

    def poll(self) -> bool:
        """One overlap attempt; returns the merged flag. Idempotent
        after the merge (the shared world is built once)."""
        if self.merged:
            return True
        probe = self._probe()
        if probe is None:
            return False
        j, ranges, pose_b = probe
        import jax.numpy as jnp
        grid_a = self.mapper_a.merged_grid()
        ranges_j = jnp.asarray(ranges)
        best_pose, best_resp = None, -1.0
        with GM.stages.stage("rendezvous.sweep"):
            for seed in self._seeds():
                res = relocalize_match(self.cfg, grid_a, ranges_j,
                                       jnp.asarray(seed))
                if bool(res.accepted):
                    r = float(res.response)
                    if r > best_resp:
                        best_resp = r
                        best_pose = np.asarray(res.pose, np.float32)
        GM.counters.inc("rendezvous.attempts")
        if best_pose is None or best_resp < self.min_response:
            with self._lock:
                self.n_attempts += 1
                self._streak.clear()
            return False
        T = se2_from_pair(best_pose, pose_b)
        with self._lock:
            self.n_attempts += 1
            self.n_accepted += 1
            if self._streak:
                t0 = self._streak[0]
                if (math.hypot(float(T[0] - t0[0]), float(T[1] - t0[1]))
                        > self.consistency_m
                        or abs(_wrap(float(T[2] - t0[2])))
                        > self.consistency_rad):
                    # Different basin than the streak head: restart the
                    # streak from THIS candidate (the Relocalizer rule).
                    self._streak.clear()
            self._streak.append(T)
            streak_len = len(self._streak)
            done = streak_len >= self.consecutive
            if done:
                verified = self._streak[-1]
                self._streak.clear()
        # Flight-recorder handshake trail, recorded AFTER the lock
        # releases (leaf-lock discipline): each accepted attempt is one
        # structured transition, so a postmortem of a wrong-basin merge
        # reads the whole verification streak, not just the outcome.
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("rendezvous_accept", robot=j,
                               streak=streak_len,
                               response=round(best_resp, 4))
        if not done:
            return False
        self._finish_merge(j, verified,
                           np.asarray(best_pose, np.float32))
        return True

    def _finish_merge(self, j: int, T: np.ndarray,
                      verified_pose: np.ndarray) -> None:
        """Build the shared world (outside `_lock`: fusion is device
        work) and publish it atomically."""
        grid, states = merge_fleets(
            self.cfg, list(self.mapper_a.states),
            list(self.mapper_b.states), T, anchor=(j, verified_pose))
        with self._lock:
            self.transform = T
            self.merged_grid = grid
            self.merged_states = states
            self.merged = True
        GM.counters.inc("rendezvous.merges")
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record(
            "rendezvous_merge", robot=j,
            transform=[round(float(v), 4) for v in T])

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "merged": self.merged,
                "n_attempts": self.n_attempts,
                "n_accepted": self.n_accepted,
                "streak": len(self._streak),
                "transform": (None if self.transform is None
                              else [round(float(v), 4)
                                    for v in self.transform]),
            }
