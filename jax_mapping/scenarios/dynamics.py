"""Scripted mutable world geometry: doors that open/close, moving crowds.

Everything the stack simulated before this module assumed ONE immutable
ground-truth bitmap. `WorldDynamics` makes the world itself scriptable
the way `resilience/faultplan.py` makes faults scriptable: the composed
world at step `t` is a PURE function of (base world, the set of held
door closures, the set of active crowds, t, seed) — no hidden state, so
two same-seed scenario runs raycast bit-identical scans.

Boundaries:

* `FaultPlan` world kinds (`door_close`, `crowd`) call
  `SimNode.set_door` / `SimNode.set_crowd`, which delegate here — the
  same existing-boundary doctrine as every other fault kind (no
  monkeypatching; the scenario path exercises the code a real dynamic
  world would).
* `SimNode.step` asks `world_if_changed(step)` each tick and re-uploads
  the composed bitmap only when geometry actually changed (a door
  toggled, a crowd moved). With nothing attached or nothing active the
  sim's hot path is byte-identical to the static-world stack.

Crowd paths are deterministic orbits: each crowd id gets a seeded
anchor, orbit radius, angular rate and phase from
`default_rng((seed, _CROWD_SALT, cid))`; its centre at step t follows
from t alone. An orbit (rather than a random walk) means the blob
KEEPS MOVING every step — the decaying mapper must both map it and
heal the trail it abandons.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from jax_mapping.sim.world import stamp_disc

_CROWD_SALT = 0x5EED


@dataclasses.dataclass(frozen=True)
class DoorSpec:
    """One door: the half-open cell rectangle [r0, r1) x [c0, c1) a
    closure fills with wall. The BASE world carries the door OPEN (the
    generator's gap); `door_close` scenario windows occupy it."""

    name: str
    r0: int
    r1: int
    c0: int
    c1: int

    def __post_init__(self):
        if self.r1 <= self.r0 or self.c1 <= self.c0:
            raise ValueError(f"door {self.name!r}: empty rectangle "
                             f"({self.r0},{self.r1})x({self.c0},{self.c1})")


class WorldDynamics:
    """Composes the live ground-truth world from scripted mutations.

    Thread-safety: mutators (FaultPlan boundary) and the composer
    (SimNode.step) run on the deterministic step clock in stepped
    stacks, but realtime stacks drive `SimNode.step` from an executor
    thread — `_lock` keeps the door/crowd registries and the change
    flag consistent either way (leaf lock: nothing is called out under
    it)."""

    def __init__(self, base_world: np.ndarray, res_m: float,
                 doors: Iterable = (), seed: int = 0):
        self.base = np.array(np.asarray(base_world, bool), copy=True)
        self.res_m = float(res_m)
        self.seed = int(seed)
        self.doors: Dict[str, DoorSpec] = {}
        for d in doors:
            spec = d if isinstance(d, DoorSpec) else DoorSpec(**d)
            if spec.name in self.doors:
                raise ValueError(f"duplicate door name {spec.name!r}")
            n = self.base.shape[0]
            if not (0 <= spec.r0 < spec.r1 <= n
                    and 0 <= spec.c0 < spec.c1 <= self.base.shape[1]):
                raise ValueError(f"door {spec.name!r} rectangle outside "
                                 f"the {self.base.shape} world")
            self.doors[spec.name] = spec
        self._lock = threading.Lock()
        #: door name -> closed flag (FaultPlan refcounts windows; this
        #: layer only sees the composed boolean).
        self._door_closed: Dict[str, bool] = {}
        #: crowd id -> radius_m of the active blob.
        self._crowds: Dict[int, float] = {}
        #: Geometry changed since the last compose (doors/crowds
        #: toggled). Crowds additionally force a recompose every step
        #: (they move).
        self._dirty = True
        self.n_recomposes = 0

    # -- mutation boundary (SimNode.set_door / set_crowd) --------------------

    def set_door(self, name: str, closed: bool) -> None:
        if name not in self.doors:
            raise ValueError(f"unknown door {name!r} "
                             f"(registered: {sorted(self.doors)})")
        with self._lock:
            if self._door_closed.get(name, False) != bool(closed):
                self._door_closed[name] = bool(closed)
                self._dirty = True

    def set_crowd(self, cid: int, radius_m: Optional[float]) -> None:
        """Activate crowd `cid` with blob radius `radius_m`, or remove
        it (None). FaultPlan composes overlapping windows by worst
        (max radius) before calling here."""
        with self._lock:
            if radius_m is None:
                if self._crowds.pop(int(cid), None) is not None:
                    self._dirty = True
            elif self._crowds.get(int(cid)) != float(radius_m):
                self._crowds[int(cid)] = float(radius_m)
                self._dirty = True

    # -- deterministic crowd paths -------------------------------------------

    def crowd_center(self, cid: int, step: int) -> Tuple[float, float]:
        """(row, col) of crowd `cid`'s centre at step `step`: a seeded
        orbit, pure in (seed, cid, step)."""
        n = self.base.shape[0]
        rng = np.random.default_rng((self.seed, _CROWD_SALT, int(cid)))
        margin = max(4.0, 0.15 * n)
        anchor_r = rng.uniform(margin, n - margin)
        anchor_c = rng.uniform(margin, n - margin)
        orbit = rng.uniform(0.06 * n, 0.18 * n)
        rate = rng.uniform(0.05, 0.15) * rng.choice((-1.0, 1.0))
        phase = rng.uniform(0.0, 2.0 * np.pi)
        a = phase + rate * step
        return (float(anchor_r + orbit * np.sin(a)),
                float(anchor_c + orbit * np.cos(a)))

    # -- composition ---------------------------------------------------------

    def world_at(self, step: int) -> np.ndarray:
        """The composed ground-truth world at step `step` (fresh
        array; the base is never mutated)."""
        with self._lock:
            closed = [self.doors[n] for n, c in self._door_closed.items()
                      if c]
            crowds = sorted(self._crowds.items())
            self._dirty = False
            self.n_recomposes += 1
        w = self.base.copy()
        for d in closed:
            w[d.r0:d.r1, d.c0:d.c1] = True
        for cid, radius_m in crowds:
            row, col = self.crowd_center(cid, step)
            stamp_disc(w, row, col, radius_m / self.res_m)
        return w

    def world_if_changed(self, step: int) -> Optional[np.ndarray]:
        """`world_at(step)` when geometry differs from the last compose
        (a toggle landed, or any crowd is active — crowds move every
        step), else None — the SimNode hot-path gate that keeps a
        quiet scenario from re-uploading an unchanged world."""
        with self._lock:
            quiet = not self._dirty and not self._crowds
        if quiet:
            return None
        return self.world_at(step)

    def snapshot(self) -> dict:
        """Scenario observability (one dict for /status-style export
        and test assertions)."""
        with self._lock:
            return {
                "doors": dict(self._door_closed),
                "crowds": dict(self._crowds),
                "n_recomposes": self.n_recomposes,
            }


def doors_from_dicts(doors: Iterable[dict]) -> List[DoorSpec]:
    """Normalize the world generators' plain-dict door reports."""
    return [d if isinstance(d, DoorSpec) else DoorSpec(**d)
            for d in doors]
