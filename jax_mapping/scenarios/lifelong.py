"""Lifelong missions: sim-accelerated day-long soaks under continuous
chaos.

A lifelong session is not a longer mission — it is a mission where
EVERYTHING cycles: doors open and close (`door_close` windows), crowds
pass through (`crowd` windows), the mapper dies and resumes from
checkpoint (supervisor restarts, bounded generation retention), and the
map must keep healing (DecayConfig) instead of fossilizing its first
hour. This module is the deterministic driver for such sessions: one
seeded scenario+chaos schedule (`day_plan`), one launch wrapper that
arms the world dynamics (`launch_scenario_stack`, in the package init),
and one mission runner returning the artifacts soak gates assert on
(`run_lifelong_mission`). Two same-seed missions are bit-identical —
the FaultPlan determinism contract extended to the world itself.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from jax_mapping.config import SlamConfig
from jax_mapping.resilience.faultplan import FaultEvent, FaultPlan


def day_plan(mission_steps: int, door_names: Sequence[str],
             n_crowds: int = 1, door_cycle: int = 60,
             crowd_cycle: int = 90, kill_steps: Sequence[int] = (),
             start: int = 10) -> List[FaultEvent]:
    """A deterministic 'day': every door cycles closed/open on a
    staggered `door_cycle` cadence, crowds churn through on
    `crowd_cycle`, and the mapper is killed at each of `kill_steps`
    (the supervisor restarts it from checkpoint). Pure scheduling —
    no RNG; the FaultPlan seed only matters if callers append
    random_plan events on top."""
    events: List[FaultEvent] = []
    for k, name in enumerate(door_names):
        first = start + k * (door_cycle // max(1, len(door_names)))
        for t in range(first, max(first + 1, mission_steps - 5),
                       door_cycle):
            events.append(FaultEvent(step=t, kind="door_close",
                                     name=name,
                                     duration=door_cycle // 2))
    for c in range(n_crowds):
        first = start + 15 + c * (crowd_cycle // max(1, n_crowds))
        for t in range(first, max(first + 1, mission_steps - 5),
                       crowd_cycle):
            events.append(FaultEvent(step=t, kind="crowd", robot=c,
                                     duration=crowd_cycle // 3,
                                     value=0.25))
    for t in kill_steps:
        events.append(FaultEvent(step=int(t), kind="kill_node",
                                 name="jax_mapper"))
    return events


@dataclasses.dataclass
class MissionReport:
    """What a soak gate asserts on — everything host-side numpy."""

    grid: np.ndarray                 # final shared log-odds map
    plan_log: List[tuple]            # the FaultPlan's (step, desc) log
    n_mapper_restarts: int
    n_scans_fused: int
    n_decay_passes: int
    n_world_updates: int
    map_revision: int
    restart_epoch: int
    checkpoint_files: List[str]      # basenames in the checkpoint dir
    health_transitions: List[tuple]
    #: Flight-recorder postmortem dumps THIS mission wrote (basenames
    #: in the checkpoint dir — supervisor restarts, watchdog
    #: divergence; obs/recorder.py). The first artifact to read after
    #: a failed soak gate: `python -m jax_mapping.obs diff` two
    #: same-seed missions' dumps for the first divergent transition.
    postmortem_dumps: List[str] = dataclasses.field(default_factory=list)
    #: SLO alert transitions THIS mission fired (obs/slo.py `slo_alert`
    #: flight events past the mission's event mark): (tick, objective,
    #: state) tuples, state "firing"/"clear" — empty when no SLO engine
    #: was armed. Deterministic fields, so two same-seed missions
    #: report identical alert schedules (the chaos-determinism
    #: contract extended to alerting).
    slo_alerts: List[tuple] = dataclasses.field(default_factory=list)
    #: Traveled-distance axis (ISSUE 18, bounded-memory soaks): total
    #: ground-truth path length over the fleet and per robot,
    #: chunk-sampled every `sample_every` steps — the x-axis the
    #: constant-device-bytes gate plots against (a lifelong corridor
    #: mission must show memory FLAT while distance grows).
    distance_traveled_m: float = 0.0
    distance_per_robot_m: List[float] = dataclasses.field(
        default_factory=list)
    #: One sample per chunk: {step, distance_m} plus — when the stack
    #: runs a windowed world — the store's live footprint
    #: (device_window_bytes, host_tiles, spill_tiles, away_tiles,
    #: origin_tile). Deterministic fields only: two same-seed missions
    #: report identical series, eviction/spill schedules included.
    world_series: List[dict] = dataclasses.field(default_factory=list)

    def known_cells(self, thresh: float = 0.5) -> int:
        return int((np.abs(self.grid) > thresh).sum())

    def peak_device_window_bytes(self) -> int:
        """Max device-resident window bytes across the series (0 when
        the mission was not windowed) — the constant-memory gate's
        subject: flat vs `distance_traveled_m` or the window leaks."""
        return max((s.get("device_window_bytes", 0)
                    for s in self.world_series), default=0)


def _mission_dumps(recorder, ev_mark: int):
    """Basenames of the dumps THIS mission triggered, derived from the
    recorder's `postmortem_dump` events past the mission's starting
    event mark — NOT from `n_dumps`/`dumps`, which advance only when a
    dump's (possibly async — mapper divergence dumps write on a
    one-shot thread) disk write completes: a count-window would miss a
    final-steps divergence dump still in flight and could attribute a
    previous mission's late write to this one. Events stamp at snapshot
    time on the triggering thread, so the window is exact; bounded by
    the event ring (a >capacity mission loses its earliest, by
    design)."""
    return [e["path"] for e in recorder.events_since(ev_mark)
            if e["kind"] == "postmortem_dump"]


def run_lifelong_mission(cfg: SlamConfig, world: np.ndarray, doors,
                         events: Sequence[FaultEvent], steps: int,
                         seed: int, checkpoint_dir: Optional[str],
                         n_robots: int = 2,
                         sample_every: int = 10,
                         goal_script: Optional[
                             Sequence[Tuple[int, float, float]]] = None
                         ) -> MissionReport:
    """Drive one deterministic lifelong mission end-to-end and report.

    Boots the scenario stack (world dynamics armed, supervisor +
    checkpoint cadence when `checkpoint_dir` is given), attaches the
    schedule as ONE FaultPlan (world kinds and process chaos are the
    same mechanism), runs `steps`, and collects the assertion surface.
    Determinism anchor: same (cfg, world, doors, events, seed, steps)
    → bit-identical report.grid and plan_log.

    The run is CHUNKED every `sample_every` steps to accumulate the
    traveled-distance axis and (windowed stacks) the world-footprint
    series — chunked `run_steps` is step-for-step identical to one
    call (the fault plan and supervisor tick on the step index), so
    the sampling changes no mission bit.

    `goal_script` is an optional sequence of `(step, x, y)` entries
    (map metres) published on `/goal_pose` — the operator goal
    ingress, addressing robot 0 — at the first chunk boundary at or
    after `step` (exact when `step` is a multiple of `sample_every`).
    A scripted patrol pins the TRAJECTORY to the step clock: manual
    goals override frontier assignment in the brain, so the path no
    longer depends on frontier-auction tie-breaks, which on symmetric
    courses sit within float noise of each other and are therefore
    the one mission input same-seed determinism cannot pin across
    processes (XLA CPU codegen may vary per process; within one
    process the contract holds regardless)."""
    from jax_mapping.bridge.messages import Pose2D
    from jax_mapping.obs.recorder import flight_recorder
    from jax_mapping.scenarios import launch_scenario_stack
    # Event mark, not a dump count: `postmortem_dump` events stamp at
    # snapshot time on the triggering thread, so the window stays exact
    # when a dump's disk write is asynchronous.
    ev_mark = flight_recorder.mark()
    st = launch_scenario_stack(cfg, world, doors=doors,
                               n_robots=n_robots, realtime=False,
                               seed=seed, checkpoint_dir=checkpoint_dir)
    try:
        st.brain.start_exploring()
        st.brain.reconnect_period_s = 0.0
        plan = FaultPlan(list(events), seed=seed)
        st.attach_fault_plan(plan)
        dist = np.zeros(n_robots)
        prev_xy = st.sim.truth_poses()[:, :2].copy()
        series: List[dict] = []
        script = sorted(goal_script or [], key=lambda e: int(e[0]))
        goal_pub = (st.bus.publisher("/goal_pose") if script else None)
        si = 0
        done = 0
        chunk = max(1, int(sample_every))
        while done < steps:
            while si < len(script) and int(script[si][0]) <= done:
                _, gx, gy = script[si]
                goal_pub.publish(Pose2D(x=float(gx), y=float(gy)))
                si += 1
            k = min(chunk, steps - done)
            st.run_steps(k)
            done += k
            cur_xy = st.sim.truth_poses()[:, :2].copy()
            dist += np.linalg.norm(cur_xy - prev_xy, axis=1)
            prev_xy = cur_xy
            entry = {"step": done, "distance_m": float(dist.sum())}
            ws = st.mapper.world_status() \
                if hasattr(st.mapper, "world_status") else None
            if ws is not None:
                entry.update(
                    device_window_bytes=int(ws["device_window_bytes"]),
                    host_tiles=int(ws["host_tiles"]),
                    away_tiles=int(ws["away_tiles"]),
                    spill_tiles=(int(ws["spill"]["tiles"])
                                 if ws.get("spill") else 0),
                    origin_tile=[int(v) for v in ws["origin_tile"]])
            series.append(entry)
        # Revision BEFORE content (the C1 ordering doctrine): a stamp
        # read after the grid could pair new content with an older
        # revision's successor and misreport the mission's final state.
        final_revision = st.mapper.map_revision
        grid = np.array(np.asarray(st.mapper.merged_grid()), copy=True)
        files = []
        if checkpoint_dir:
            # Files only: the flight recorder's `postmortem/` subdir
            # (obs/) shares the checkpoint dir but is not a generation.
            files = sorted(os.path.basename(p) for p in
                           glob.glob(os.path.join(checkpoint_dir, "*"))
                           if os.path.isfile(p))
        return MissionReport(
            grid=grid,
            plan_log=list(plan.log),
            n_mapper_restarts=(st.supervisor.n_restarts("jax_mapper")
                               if st.supervisor is not None else 0),
            n_scans_fused=st.mapper.n_scans_fused,
            n_decay_passes=st.mapper.n_decay_passes,
            n_world_updates=st.sim.n_world_updates,
            map_revision=final_revision,
            restart_epoch=st.mapper.restart_epoch,
            checkpoint_files=files,
            health_transitions=(list(st.health.transitions)
                                if st.health is not None else []),
            postmortem_dumps=_mission_dumps(flight_recorder, ev_mark),
            slo_alerts=[(e.get("tick"), e.get("objective"),
                         e.get("state"))
                        for e in flight_recorder.events_since(ev_mark)
                        if e["kind"] == "slo_alert"],
            distance_traveled_m=float(dist.sum()),
            distance_per_robot_m=[float(d) for d in dist],
            world_series=series,
        )
    finally:
        st.shutdown()
