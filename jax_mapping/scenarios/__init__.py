"""Scenario engine: dynamic worlds, rendezvous fleet merges, lifelong
missions (ISSUE 8 / ROADMAP item 5).

The resilience/recovery stack made the system fault-tolerant; this
package makes it WORLD-tolerant. Scenarios are scripted the same way
faults are — seeded, windowed, refcount-composed FaultPlan events —
so a dynamic world is just more chaos on the same deterministic step
clock:

* `dynamics.WorldDynamics` — scripted mutable ground truth (doors that
  open/close, seeded moving crowd blobs), injected through the
  `door_close`/`crowd` FaultPlan kinds at the SimNode boundary; the
  decaying mapper (DecayConfig) heals the stale evidence they leave.
* `rendezvous.RendezvousMerger` — two independently-seeded fleets with
  unknown relative origin detect map overlap via the wide-window
  cross-fleet sweep, verify the implied rigid transform by streak, and
  merge grids + pose graphs into one shared world.
* `lifelong` — deterministic day-long soak driving: door cycles, crowd
  churn, supervisor mapper restarts with bounded checkpoint retention,
  one MissionReport to assert on.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from jax_mapping.config import SlamConfig
from jax_mapping.scenarios.dynamics import (                  # noqa: F401
    DoorSpec, WorldDynamics, doors_from_dicts,
)
from jax_mapping.scenarios.lifelong import (                  # noqa: F401
    MissionReport, day_plan, run_lifelong_mission,
)
from jax_mapping.scenarios.rendezvous import (                # noqa: F401
    RendezvousMerger, merge_fleets, merged_frontier_assignment,
    se2_apply, se2_from_pair, transform_state,
)


def launch_scenario_stack(cfg: SlamConfig, world: np.ndarray,
                          doors=(), world_res_m: Optional[float] = None,
                          seed: int = 0, **launch_kwargs):
    """`launch_sim_stack` with the world made scriptable: builds the
    stack, then arms a `WorldDynamics` over the SAME world bitmap with
    the given door registry (dicts from the world generators or
    DoorSpecs) and the launch seed (crowd paths derive from it). With
    no events ever fired the composed world equals the base world —
    the scenario wiring is bit-inert (the scenario bit-exactness
    property test pins this)."""
    from jax_mapping.bridge.launch import launch_sim_stack
    st = launch_sim_stack(cfg, world, world_res_m=world_res_m,
                          seed=seed, **launch_kwargs)
    dyn = WorldDynamics(world, st.sim.world_res_m,
                        doors=doors_from_dicts(doors), seed=seed)
    st.sim.attach_world_dynamics(dyn)
    return st
