"""jax_mapping — TPU-native distributed exploration & mapping framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
ROS 2 stack (rafaelgmv/Distributed-Autonomous-Exploration-and-Mapping):
occupancy-grid SLAM, correlative scan matching, loop closure, frontier
exploration, multi-robot fleet scaling, live map serving, and robot control —
re-designed TPU-first.

Layout (mirrors SURVEY.md §7 build plan):
  ops/       pure-JAX device kernels (grid fusion, scan match, frontier, pose graph)
  models/    composed pipelines (SlamModel, FleetModel, explorer policies)
  parallel/  mesh construction, shard_map fleet step, collectives
  bridge/    ROS-shaped node graph: messages, pub/sub bus, TF tree, Flask API
  sim/       simulated Thymio fleet + synthetic LD06 LiDAR
  io/        checkpoint/resume, trace record/replay
  utils/     profiling, config/units, testing helpers
  native/    C++ host-side components (LD06 packet parser/filter)
"""

__version__ = "0.1.0"

from jax_mapping.config import GridConfig, RobotConfig, SlamConfig  # noqa: F401
