"""Multi-chip fleet step: shard_map over a ('fleet', 'space') mesh.

The distribution design (SURVEY.md §2.4 mapping, scaling-book recipe —
pick a mesh, annotate shardings, let XLA insert collectives):

  axis 'fleet' — robots are data-parallel. Sensing, matching, patch
      classification and the explorer policy never communicate; the ONLY
      fleet-wide exchange is (a) one psum merging per-robot log-odds slab
      contributions (the on-device analog of the reference's DDS fan-in of
      every robot's /scan into one SLAM node) and (b) one all_gather of the
      small robot->cluster cost matrix so the greedy auction sees the whole
      fleet.

  axis 'space' — the grid lives sharded by row slabs. The dense inverse
      sensor model is cell-local, so each slab evaluates every local robot's
      patch restricted to its own rows with NO halo exchange (SURVEY.md §7
      "sharded grid halos" solved by construction). The matcher needs map
      context around each robot, obtained with one tiled all_gather along
      'space'; frontier work coarsens slabs locally and all_gathers only the
      (size/downsample)^2 coarse masks.

Collectives per step: all_gather(grid, 'space'), psum(slab deltas, 'fleet'),
all_gather(coarse masks, 'space'), all_gather(costs, 'fleet') — all riding
ICI on a real pod.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax_mapping.config import SlamConfig, ensure_valid_mode
from jax_mapping.models.explorer import frontier_policy
from jax_mapping.models.fleet import (_cross_candidates, _update_graphs,
                                      _verify_and_optimize)
from jax_mapping.models.slam import _verify_loop
from jax_mapping.ops import frontier as F
from jax_mapping.ops import grid as G
from jax_mapping.ops import posegraph as PG
from jax_mapping.ops import scan_match as M
from jax_mapping.ops.odometry import pose_between, rk2_step, wrap_angle
from jax_mapping.sim import lidar, thymio

Array = jax.Array


class ShardedFleetState(NamedTuple):
    """Global-view pytree; sharding applied via NamedSharding on creation."""
    true_poses: Array     # (R, 3)   P('fleet', None)
    wheel_speeds: Array   # (R, 2)   P('fleet', None)
    keys: Array           # (R,)     P('fleet',)  per-robot PRNG keys
    est_poses: Array      # (R, 3)   P('fleet', None)
    grid: Array           # (N, N)   P('space', None)
    exploring: Array      # (R,)     P('fleet',)
    last_key_poses: Array  # (R, 3)  P('fleet', None)
    graphs: PG.PoseGraph  # per-robot graphs, leading (R,) axis, P('fleet',…)
    scan_rings: Array     # (R, max_poses, beams) P('fleet', None, None)
    n_loops: Array        # (R,)     P('fleet')
    t: Array              # ()       replicated


def _fleet_spec(x) -> P:
    """P('fleet', None, ...) matching a leaf's rank."""
    return P("fleet", *([None] * (x.ndim - 1)))


def state_specs(cfg: SlamConfig) -> ShardedFleetState:
    graphs0 = PG.empty_graph(cfg.loop)
    graph_specs = jax.tree.map(
        lambda leaf: P("fleet", *([None] * leaf.ndim)), graphs0)
    return ShardedFleetState(
        true_poses=P("fleet", None),
        wheel_speeds=P("fleet", None),
        keys=P("fleet"),
        est_poses=P("fleet", None),
        grid=P("space", None),
        exploring=P("fleet"),
        last_key_poses=P("fleet", None),
        graphs=graph_specs,
        scan_rings=P("fleet", None, None),
        n_loops=P("fleet"),
        t=P(),
    )


def init_sharded_state(cfg: SlamConfig, mesh: Mesh, seed: int = 0
                       ) -> ShardedFleetState:
    R = cfg.fleet.n_robots
    ang = jnp.linspace(0, 2 * jnp.pi, R, endpoint=False)
    r = 0.4 + 0.2 * (jnp.arange(R) % 3) / 3.0
    poses = jnp.stack([r * jnp.cos(ang), r * jnp.sin(ang), ang], -1)
    state = ShardedFleetState(
        true_poses=poses.astype(jnp.float32),
        wheel_speeds=jnp.zeros((R, 2), jnp.float32),
        keys=jax.vmap(lambda i: jax.random.fold_in(
            jax.random.PRNGKey(seed), i))(jnp.arange(R)),
        est_poses=poses.astype(jnp.float32),
        grid=G.empty_grid(cfg.grid),
        exploring=jnp.ones((R,), bool),
        last_key_poses=jnp.full((R, 3), 1e9, jnp.float32),
        graphs=jax.vmap(lambda _: PG.empty_graph(cfg.loop))(jnp.arange(R)),
        scan_rings=jnp.zeros((R, cfg.loop.max_poses, cfg.scan.padded_beams),
                             jnp.float32),
        n_loops=jnp.zeros((R,), jnp.int32),
        t=jnp.int32(0),
    )
    specs = state_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs,
        is_leaf=lambda x: isinstance(x, (jnp.ndarray, jax.Array)))


def _slab_delta(cfg: SlamConfig, scans: Array, poses: Array,
                slab_row0: Array, slab_rows: int,
                mask: Array = None) -> Array:
    """Per-scan patches -> one (slab_rows, N) delta restricted to this slab.

    A patch at global row origin o lands at canvas row o - slab_row0 + Pp
    in a (slab_rows + 2*Pp, N) canvas; non-overlapping patches clip into the
    discarded margins, overlap slices out exactly. Sequential fold keeps
    overlapping patches deterministic (no scatter). `mask` (B,) zeroes
    masked scans' contributions (the key-scan gate / ring validity)."""
    g, s = cfg.grid, cfg.scan
    Pp = g.patch_cells
    N = g.size_cells
    origins = jax.vmap(lambda p: G.patch_origin(g, p[:2]))(poses)
    deltas = jax.vmap(
        lambda r, p, o: G.classify_patch(g, s, r, p, o))(scans, poses, origins)
    if mask is not None:
        deltas = deltas * mask[:, None, None].astype(deltas.dtype)

    canvas = jnp.zeros((slab_rows + 2 * Pp, N), jnp.float32)

    def body(cv, do):
        delta, origin = do
        ro = jnp.clip(origin[0] - slab_row0 + Pp, 0, slab_rows + Pp)
        cur = jax.lax.dynamic_slice(cv, (ro, origin[1]), (Pp, Pp))
        return jax.lax.dynamic_update_slice(cv, cur + delta,
                                            (ro, origin[1])), None

    canvas, _ = jax.lax.scan(body, canvas, (deltas, origins))
    return canvas[Pp:Pp + slab_rows]


def make_fleet_step(cfg: SlamConfig, mesh: Mesh, world_res_m: float):
    """Build the jitted sharded step: (state, world) -> (state, metrics)."""
    ensure_valid_mode(cfg)
    n_space = mesh.shape["space"]
    n_fleet = mesh.shape["fleet"]
    N = cfg.grid.size_cells
    slab_rows = N // n_space
    R = cfg.fleet.n_robots
    if R % n_fleet:
        raise ValueError(f"n_robots={R} not divisible by fleet axis {n_fleet}")
    n_samples = max(8, int(cfg.scan.range_max_m / (world_res_m * 0.5)))
    dt = 1.0 / cfg.robot.control_rate_hz
    d = cfg.frontier.downsample
    if slab_rows % d:
        raise ValueError("slab rows must be divisible by frontier downsample")

    def step(state: ShardedFleetState, world: Array):
        # Per-device views: robots R/n_fleet, grid slab (slab_rows, N).
        slab_idx = jax.lax.axis_index("space")
        slab_row0 = slab_idx * slab_rows

        # 1. Sense (local robots, replicated world).
        scans = lidar.simulate_scans(cfg.scan, world, world_res_m,
                                     n_samples, state.true_poses)
        prox = lidar.ir_proximity(world, world_res_m, state.true_poses)

        # 2. Frontier: coarsen own slab, gather coarse masks along 'space'.
        free_s, _occ_s, unk_s = F.coarsen(cfg.frontier, cfg.grid, state.grid)
        free = jax.lax.all_gather(free_s, "space", axis=0, tiled=True)
        unk = jax.lax.all_gather(unk_s, "space", axis=0, tiled=True)
        fr = F.compute_frontiers_from_masks(cfg.frontier, cfg.grid,
                                            free, unk, state.est_poses)
        # Fleet-wide auction: gather every robot's costs, auction, slice.
        costs_all = jax.lax.all_gather(fr.costs, "fleet", axis=0, tiled=True)
        assign_all = F.assign_frontiers(costs_all)
        my = jax.lax.axis_index("fleet") * (R // n_fleet)
        assignment = jax.lax.dynamic_slice_in_dim(assign_all, my,
                                                  R // n_fleet)
        goals = fr.targets[jnp.clip(assignment, 0)]
        goal_valid = assignment >= 0
        if cfg.frontier.planned_goals:
            # Planned steering from the SAME gathered coarse masks the
            # assignment used — local robots only, no extra collectives.
            wps, wvalid = F.assigned_waypoints_from_masks(
                cfg.frontier, cfg.grid, free, unk, state.est_poses,
                fr.targets, assignment)
            goals = jnp.where(wvalid[:, None], wps, goals)

        # 3. Policy (local).
        pol = frontier_policy(cfg.robot, cfg.scan, state.est_poses, goals,
                              goal_valid, scans, prox, state.exploring)

        # 4. Kinematics (local, per-robot keys).
        tp, ws, keys, measured = thymio.step_robots_keyed(
            cfg.robot, state.true_poses, state.wheel_speeds, state.keys,
            pol.targets.astype(jnp.float32), dt)

        # 5. Odometry + gated matching against the gathered full grid.
        est = jax.vmap(lambda p, w: rk2_step(cfg.robot, p, w[0], w[1], dt))(
            state.est_poses, measured)
        full_grid = jax.lax.all_gather(state.grid, "space", axis=0,
                                       tiled=True)
        d_trav = jnp.linalg.norm(est[:, :2] - state.last_key_poses[:, :2],
                                 axis=-1)
        d_head = jnp.abs(wrap_angle(est[:, 2] - state.last_key_poses[:, 2]))
        is_key = (d_trav > cfg.matcher.min_travel_m) | \
            (d_head > cfg.matcher.min_heading_rad)
        res = M.match_batch(cfg.grid, cfg.scan, cfg.matcher, full_grid,
                            scans, est)
        est = jnp.where((is_key & res.accepted)[:, None], res.pose, est)

        if cfg.mode == "localization":
            # Frozen-map mode (models/fleet.fleet_step's gate, sharded):
            # corrections stand, nothing fuses, graphs never grow,
            # closures never fire — and the skipped sections' psums
            # vanish uniformly across the mesh (static config), so no
            # shard waits on a collective another shard compiled out.
            grid = state.grid
            graphs, rings = state.graphs, state.scan_rings
            closed = jnp.zeros_like(is_key)
        else:
            # 6. Fuse: local KEY robots' slab contributions, psum over
            # 'fleet'.
            delta = _slab_delta(cfg, scans, est, slab_row0, slab_rows,
                                mask=is_key)
            delta = jax.lax.psum(delta, "fleet")
            grid = jnp.clip(state.grid + delta, cfg.grid.logodds_min,
                            cfg.grid.logodds_max)

            # 7. Pose graphs (local robots) + loop closure. The heavy
            # verification runs under ONE cond whose predicate is psum'd
            # so it is uniform across the mesh; the branch itself
            # contains NO collectives (psums happen outside), so the
            # cond cannot deadlock.
            graphs, rings, k_idx = _update_graphs(cfg, state.graphs, est,
                                                  is_key, scans,
                                                  state.scan_rings)
            cand, found = jax.vmap(
                lambda g, q: PG.loop_candidate(cfg.loop, g, q))(graphs,
                                                                k_idx)
            attempt = is_key & found & bool(cfg.loop.enabled)
            # Cross-robot relocalization stays SHARD-LOCAL: candidates
            # come from this shard's graphs only (a fleet-wide search
            # would drag every shard's rings through collectives;
            # locality is the trade the fleet axis buys — see
            # models/fleet._cross_candidates).
            xrobot, xcand, xfound = _cross_candidates(cfg, graphs, est)
            xattempt = is_key & ~res.accepted & xfound & ~attempt & \
                bool(cfg.loop.enabled) & bool(cfg.loop.cross_robot)
            attempt_any_local = attempt | xattempt
            any_attempt = jax.lax.psum(attempt_any_local.sum(),
                                       "fleet") > 0
            # Rings are complete by construction: a full ring thins
            # before any append (_update_graphs), uniformly across
            # shards (thinning depends only on shard-local state) —
            # repair never stops.

            def close(args):
                graphs, est = args
                graphs3, est2, closed = _verify_and_optimize(
                    cfg, graphs, rings, est, scans, k_idx, cand, attempt,
                    xrobot, xcand, xattempt)
                # Local repair slab from this shard's rings (psum'd
                # OUTSIDE — the cond branches stay collective-free).
                Rl, cap, beams = rings.shape
                repair = _slab_delta(
                    cfg, rings.reshape(Rl * cap, beams),
                    graphs3.poses[:, :cap].reshape(Rl * cap, 3),
                    slab_row0, slab_rows,
                    mask=graphs3.pose_valid[:, :cap].reshape(-1))
                return graphs3, est2, closed, repair

            def skip(args):
                graphs, est = args
                zero = jnp.zeros((slab_rows, N), jnp.float32)
                return graphs, est, jnp.zeros_like(attempt), zero

            graphs, est, closed, repair = jax.lax.cond(
                any_attempt, close, skip, (graphs, est))
            any_closed = jax.lax.psum(closed.sum(), "fleet") > 0
            repair = jax.lax.psum(repair, "fleet")
            grid = jnp.where(any_closed,
                             jnp.clip(repair, cfg.grid.logodds_min,
                                      cfg.grid.logodds_max), grid)

        last_key = jnp.where(is_key[:, None], est, state.last_key_poses)
        state2 = ShardedFleetState(
            true_poses=tp, wheel_speeds=ws, keys=keys, est_poses=est,
            grid=grid, exploring=state.exploring, last_key_poses=last_key,
            graphs=graphs, scan_rings=rings,
            n_loops=state.n_loops + closed.astype(jnp.int32),
            t=state.t + 1)
        # Scalar fleet metrics (psum'd so they are true fleet aggregates).
        err = jnp.sum(jnp.linalg.norm(est[:, :2] - tp[:, :2], axis=-1))
        err = jax.lax.psum(err, "fleet") / R
        resp = jax.lax.psum(jnp.sum(res.response), "fleet") / R
        n_loops_total = jax.lax.psum(state2.n_loops.sum(), "fleet")
        # Thin events THIS step, observed at the trigger condition
        # (_update_graphs thins exactly when a key add finds the ring
        # full) — the dry run's proof that thinning fired across the
        # mesh cannot be inferred from n_poses alone (it is bounded by
        # capacity whether or not the thin ran).
        thins = is_key & (state.graphs.n_poses >= cfg.loop.max_poses)
        metrics = {"mean_pose_err_m": err, "mean_match_response": resp,
                   "n_clusters": jnp.sum(fr.sizes > 0),
                   "n_loops": n_loops_total,
                   "n_thins": jax.lax.psum(thins.sum(), "fleet")}
        return state2, metrics

    specs = state_specs(cfg)
    sharded = jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, P(None, None)),
        out_specs=(specs, {"mean_pose_err_m": P(), "mean_match_response": P(),
                           "n_clusters": P(), "n_loops": P(),
                           "n_thins": P()}),
        check_vma=False)
    return jax.jit(sharded)
