"""Device-mesh construction helpers.

Axes:
  'fleet' — data parallelism over robots: per-robot sensing/matching/patch
            classification are independent; map contributions merge with a
            single psum (the on-device replacement for the reference's DDS
            fan-in of /scan to one SLAM process, SURVEY.md §2.4).
  'space' — the occupancy grid sharded by row blocks: each device owns a
            horizontal slab of the world (halo-free by construction: the
            inverse sensor model is cell-local, so a slab can evaluate any
            robot's patch restricted to its own rows).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh


def factor_devices(n: int) -> tuple[int, int]:
    """Split n devices into (fleet, space) as square-ish as possible,
    preferring more fleet parallelism (robot count usually exceeds the
    useful number of grid slabs)."""
    best = (n, 1)
    for space in range(1, int(math.isqrt(n)) + 1):
        if n % space == 0:
            best = (n // space, space)
    return best


def make_mesh(n_fleet: Optional[int] = None, n_space: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('fleet', 'space') mesh over the available devices."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n_fleet is None and n_space is None:
        n_fleet, n_space = factor_devices(n)
    elif n_fleet is None:
        n_fleet = n // n_space
    elif n_space is None:
        n_space = n // n_fleet
    if n_fleet * n_space != n:
        raise ValueError(
            f"mesh {n_fleet}x{n_space} != {n} devices available")
    import numpy as np
    arr = np.array(devs).reshape(n_fleet, n_space)
    return Mesh(arr, axis_names=("fleet", "space"))
