"""Mesh construction, shard_map fleet step, collectives.

The reference's distribution is DDS pub/sub between two hosts (SURVEY.md
§2.4); here distribution is XLA collectives over a jax.sharding.Mesh:
robots data-parallel along a 'fleet' axis (psum map merge), the grid
spatially sharded along a 'space' axis (the spatial analog of sequence
parallelism, SURVEY.md §5).
"""
