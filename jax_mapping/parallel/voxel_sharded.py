"""Multi-chip 3D voxel fusion: the grid sharded in Y slabs, ZERO
collectives per step (BASELINE.json configs[4]: "... pmap over v5e pod").

Design — why this beats translating a pod-parallel OctoMap:

The 2D fleet path (fleet_sharded.py) works patch-wise and pays an
all_gather to give every device matcher context. The voxel pipeline has
no matcher; fusion is the whole job, and the inverse sensor model is
PURELY VOXEL-LOCAL (ops/voxel.classify_region: per-voxel math + one
depth-image gather). So the pod-scale layout is the textbook one from the
scaling-book recipe: shard the big array (the (Z, Y, X) grid along Y),
replicate the small ones (depth images: a (B, H, W) batch is ~150 KB vs
the 256 MB grid), and let every device evaluate the model restricted to
its own rows. No halos (voxel-local model), no psum (each voxel owned by
exactly one device), no gather — the only inter-chip traffic is the
depth-image broadcast, which XLA handles at dispatch.

Per-device work is the dense model over a (Z, Y/n_space, X) slab per
image — more voxels than the patch path touches, but embarrassingly
parallel, fully fused by XLA (broadcasted rank-1 geometry + gather +
selects), and free of the patch path's sequential fold: slabs accumulate
image deltas with pure adds, so the per-step latency is
O(B * Z * Y * X / n_devices) elementwise work with perfect scaling.

`shard_map` over a ('fleet', 'space') mesh: 'space' splits the Y axis;
'fleet' (if > 1) splits the image batch, and the one psum in that variant
merges batch shards' deltas — still collective-free along 'space'.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax_mapping.config import DepthCamConfig, VoxelConfig
from jax_mapping.ops import voxel as V

Array = jax.Array


def voxel_sharding(mesh: Mesh) -> NamedSharding:
    """The grid's layout: (Z, Y, X) with Y split along 'space'."""
    return NamedSharding(mesh, P(None, "space", None))


def init_sharded_voxel_grid(vox: VoxelConfig, mesh: Mesh) -> Array:
    """All-unknown voxel grid laid out across the mesh."""
    n_space = mesh.shape["space"]
    if vox.size_y_cells % n_space:
        raise ValueError(
            f"size_y_cells={vox.size_y_cells} not divisible by the "
            f"'space' axis ({n_space})")
    return jax.device_put(V.empty_voxel_grid(vox), voxel_sharding(mesh))


def make_voxel_fuse_step(vox: VoxelConfig, cam: DepthCamConfig,
                         mesh: Mesh) -> Callable[[Array, Array, Array], Array]:
    """Build the jitted sharded fuse: (grid, depths_b, poses_b) -> grid.

    depths_b: (B, H, W), poses_b: (B, 3) [x, y, yaw]; B must divide the
    'fleet' axis size. Per 'fleet' shard the local batch's slab deltas
    accumulate with adds; one psum over 'fleet' merges batch shards (a
    no-op when fleet == 1); clamping applies once per step (the window
    semantics of grid.fuse_scans_window).
    """
    V._check_patch_coverage(vox, cam)
    n_fleet = mesh.shape["fleet"]
    n_space = mesh.shape["space"]
    slab_rows = vox.size_y_cells // n_space

    # Engine choice (static, at trace time): on TPU the Pallas region
    # kernel (ops/voxel_kernel.region_delta — the patch kernel's
    # factorized gather over this device's whole Y slab); elsewhere the
    # parity-tested XLA classify. Same per-image delta either way, so
    # the 'space'-collective-free property is engine-independent.
    # Gate = platform policy + the SLAB's own support predicate — NOT
    # voxel._use_pallas, whose patch-shape constraint is irrelevant here
    # and would silently fall back for slab-supported configs.
    from jax_mapping.ops.grid import _use_pallas as _grid_use_pallas
    use_kernel = _grid_use_pallas()
    if use_kernel:
        from jax_mapping.ops import voxel_kernel as VKK
        use_kernel = VKK.region_supported(vox, cam, slab_rows,
                                          vox.size_x_cells)

    def _local(grid_slab: Array, depths: Array, poses: Array) -> Array:
        # Which rows this device owns.
        y0 = jax.lax.axis_index("space").astype(jnp.int32) * slab_rows

        if use_kernel:
            # Accumulates over the LOCAL (fleet-sharded) batch; already
            # fleet-varying (derived from the sharded depths), so the
            # psum below merges batch shards exactly like the XLA scan.
            delta = VKK.region_delta(vox, cam, depths, poses, y0,
                                     slab_rows, vox.size_x_cells)
        else:
            def one(depth, pose):
                pos, R = V.camera_pose(pose[0], pose[1], pose[2], cam)
                return V.classify_region(vox, cam, depth, pos, R,
                                         y0, jnp.int32(0),
                                         slab_rows, vox.size_x_cells)

            def body(acc, dp):
                return acc + one(*dp), None
            # The accumulator varies over 'fleet' (it sums fleet-sharded
            # images); the grid slab does not — mark the init accordingly
            # or shard_map rejects the scan carry. Unconditional (a
            # size-1 'fleet' axis still tags in_specs values as
            # fleet-varying), and the matching psum is a no-op at size 1.
            init = jax.lax.pcast(jnp.zeros_like(grid_slab), ("fleet",),
                                 to="varying")
            delta, _ = jax.lax.scan(body, init, (depths, poses))
        delta = jax.lax.psum(delta, "fleet")
        return jnp.clip(grid_slab + delta, vox.logodds_min, vox.logodds_max)

    shmapped = jax.jit(jax.shard_map(
        _local, mesh=mesh,
        in_specs=(P(None, "space", None), P("fleet", None, None), P("fleet", None)),
        out_specs=P(None, "space", None)))

    def fuse(grid: Array, depths_b: Array, poses_b: Array) -> Array:
        if depths_b.shape[0] % n_fleet:
            raise ValueError(
                f"batch {depths_b.shape[0]} not divisible by the 'fleet' "
                f"axis ({n_fleet})")
        return shmapped(grid, depths_b, poses_b)

    return fuse
