"""Multi-host distributed backend: DCN process group + hybrid meshes.

The reference's distributed backend is DDS over Wi-Fi — CycloneDDS RMW,
domain 42, Best-Effort QoS on `/scan` (`/root/reference/README.md:28,78-86`,
report.pdf §III.B/§V.A; SURVEY.md §5 "Distributed communication backend").
The TPU framework's equivalent is XLA collectives: ICI inside a pod slice,
DCN between hosts, set up by `jax.distributed`. This module is the
framework's one place that knows how to bring that up:

  * `DistConfig.from_env()` — coordinator/process-count/process-id from the
    standard JAX env vars (or the framework's `JAX_MAPPING_*` aliases),
    mirroring how the reference carries `ROS_DOMAIN_ID` in the environment
    (`pi/Dockerfile:3`);
  * `initialize()` — idempotent `jax.distributed.initialize`, a no-op for
    single-process runs so every entry point can call it unconditionally;
  * `hybrid_fleet_mesh()` — ('fleet', 'space') mesh where the *fleet* axis
    spans hosts over DCN and the *space* axis stays inside a host on ICI.

Axis placement rationale (the scaling-book recipe applied to mapping): the
fleet axis communicates once per step — a psum map-merge of log-odds deltas
— which is bandwidth-bound and latency-tolerant, exactly what DCN offers;
the space axis exchanges slab halos / gathered matcher context inside the
step's critical path, so it must ride ICI.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
from jax.sharding import Mesh

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """Process-group wiring; fields mirror jax.distributed.initialize."""

    coordinator_address: Optional[str] = None   # "host:port"
    num_processes: int = 1
    process_id: int = 0

    @staticmethod
    def from_env(env=None) -> "DistConfig":
        """JAX_MAPPING_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID, falling
        back to the standard JAX names used by launchers."""
        e = os.environ if env is None else env

        def pick(*names, default=None):
            for n in names:
                if e.get(n):
                    return e[n]
            return default

        coord = pick("JAX_MAPPING_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
        nproc = int(pick("JAX_MAPPING_NUM_PROCESSES", "JAX_NUM_PROCESSES",
                         default="1"))
        pid = int(pick("JAX_MAPPING_PROCESS_ID", "JAX_PROCESS_ID",
                       default="0"))
        return DistConfig(coordinator_address=coord, num_processes=nproc,
                          process_id=pid)


def initialize(cfg: Optional[DistConfig] = None) -> bool:
    """Bring up the DCN process group; returns True if multi-host.

    Idempotent; a single-process config is a no-op so entry points call
    this unconditionally (the reference's nodes likewise assume DDS is
    just *there* once ROS_DOMAIN_ID is set).
    """
    global _initialized
    cfg = cfg or DistConfig.from_env()
    if cfg.num_processes <= 1:
        return False
    if cfg.coordinator_address is None:
        # Half-configured multi-host must fail loudly: silently degrading
        # to independent processes would skip the fleet psum map-merge and
        # every host would build its own divergent map with no error.
        raise ValueError(
            f"num_processes={cfg.num_processes} but no coordinator address "
            f"set (JAX_MAPPING_COORDINATOR / JAX_COORDINATOR_ADDRESS)")
    if _initialized:
        return True
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id)
    _initialized = True
    return True


def hybrid_fleet_mesh(n_hosts: Optional[int] = None,
                      space_per_host: Optional[int] = None) -> Mesh:
    """('fleet', 'space') mesh with fleet across hosts (DCN) and space
    within a host (ICI).

    Single-host (or single-process) setups degrade to the local mesh
    factoring. Multi-host: each host contributes `space_per_host` devices
    to the space axis and `local devices / space_per_host` rows to the
    fleet axis; fleet-axis neighbours on different hosts communicate over
    DCN, which only carries the once-per-step psum map merge.
    """
    import numpy as np

    from jax_mapping.parallel.mesh import factor_devices, make_mesh

    n_hosts = n_hosts if n_hosts is not None else jax.process_count()
    if n_hosts <= 1:
        return make_mesh()

    local = jax.local_device_count()
    if space_per_host is None:
        _, space_per_host = factor_devices(local)
    if local % space_per_host:
        raise ValueError(f"{local} local devices not divisible by "
                         f"space_per_host={space_per_host}")
    fleet_per_host = local // space_per_host

    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(fleet_per_host, space_per_host),
            dcn_mesh_shape=(n_hosts, 1))
    except Exception:                               # noqa: BLE001
        # Fallback: order devices by process so the fleet axis still maps
        # host-major (each host's block is contiguous -> space stays local).
        devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        arr = np.array(devs).reshape(n_hosts * fleet_per_host,
                                     space_per_host)
    return Mesh(np.asarray(arr).reshape(-1, space_per_host),
                axis_names=("fleet", "space"))
