"""Persistence: checkpoint/resume + trace record/replay.

The reference has neither (SURVEY.md §5): map state lives inside
slam_toolbox's process and dies with it, and there is no recorded-data test
path. Both are first-class here — device state is a pytree of fixed-shape
arrays, so checkpointing is trivial and exact, and traces are the
golden-test backbone (SURVEY.md §4 "Implication for the TPU build").
"""

from jax_mapping.io.checkpoint import (  # noqa: F401
    CheckpointCorrupt, load_checkpoint, load_checkpoint_with_fallback,
    previous_checkpoint_path, save_checkpoint,
)
from jax_mapping.io.trace import TraceRecorder, TraceReplayer  # noqa: F401
