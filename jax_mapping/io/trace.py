"""Trace record/replay: the framework's rosbag.

SURVEY.md §4 prescribes golden-trace tests "replaying recorded /scan+/odom
through the JAX kernels" — the validation path the reference covered only
with workshop floor time. `TraceRecorder` taps bus topics; `TraceReplayer`
re-publishes a saved trace in stamp order (fast-forward or realtime), so a
single recorded run becomes a deterministic regression fixture, and a live
run on hardware becomes a reproducible offline dataset.

Format: one `.npz` — a JSON index of records (topic, stamp, message type,
scalar fields) plus each array field stored under `r<i>.<field>`. No pickle
anywhere (traces may come from untrusted robots).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from jax_mapping.bridge import messages as M
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.qos import QoSProfile, Reliability

_INDEX_KEY = "__trace_index__"

#: Message types allowed in traces (no-pickle allowlist).
_TYPES = {
    "LaserScan": M.LaserScan,
    "Odometry": M.Odometry,
    "OccupancyGrid": M.OccupancyGrid,
    "TransformStamped": M.TransformStamped,
    "FrontierArray": M.FrontierArray,
    "DepthImage": M.DepthImage,
}


def _split_msg(msg: Any) -> tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Dataclass -> (json-able scalars incl. nested, array fields)."""
    scalars: Dict[str, Any] = {}
    arrays: Dict[str, np.ndarray] = {}
    for f in dataclasses.fields(msg):
        v = getattr(msg, f.name)
        if isinstance(v, np.ndarray):
            arrays[f.name] = v
        elif dataclasses.is_dataclass(v):
            sub_s, sub_a = _split_msg(v)
            scalars[f.name] = {"__nested__": type(v).__name__, **sub_s}
            for k, a in sub_a.items():
                arrays[f"{f.name}.{k}"] = a
        else:
            scalars[f.name] = v
    return scalars, arrays


_NESTED_TYPES = {
    "Header": M.Header, "Pose2D": M.Pose2D, "Twist": M.Twist,
    "MapMetaData": M.MapMetaData,
}


def _join_msg(type_name: str, scalars: Dict[str, Any],
              arrays: Dict[str, np.ndarray]) -> Any:
    cls = _TYPES.get(type_name) or _NESTED_TYPES[type_name]
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name in arrays:
            kwargs[f.name] = arrays[f.name]
        elif f.name in scalars:
            v = scalars[f.name]
            if isinstance(v, dict) and "__nested__" in v:
                sub = dict(v)
                sub_type = sub.pop("__nested__")
                sub_arrays = {
                    k[len(f.name) + 1:]: a for k, a in arrays.items()
                    if k.startswith(f.name + ".")}
                kwargs[f.name] = _join_msg(sub_type, sub, sub_arrays)
            else:
                kwargs[f.name] = v
    return cls(**kwargs)


class TraceRecorder:
    """Subscribe to `topics` and accumulate every sample, reliably (a bag
    must not drop; QoS depth is large and Reliable)."""

    def __init__(self, bus: Bus, topics: Sequence[str]):
        self.records: List[tuple[float, str, Any]] = []
        self._subs = []
        for topic in topics:
            self._subs.append(bus.subscribe(
                topic, QoSProfile(depth=100000,
                                  reliability=Reliability.RELIABLE),
                callback=lambda msg, t=topic: self._on(t, msg)))

    def _on(self, topic: str, msg: Any) -> None:
        stamp = getattr(getattr(msg, "header", None), "stamp", None)
        if stamp is None:
            stamp = time.monotonic()
        self.records.append((stamp, topic, msg))

    def stop(self) -> None:
        for s in self._subs:
            s.close()

    def save(self, path: str, config_json: Optional[str] = None) -> int:
        """Write the bag; returns the record count.

        config_json: optional SlamConfig.to_json() of the recording run,
        so replay tooling can detect config drift (shape-incompatible
        scans fused silently otherwise). Stored as a wrapper dict; bags
        written by older versions (bare list index) still load.
        """
        index = []
        arrays: Dict[str, np.ndarray] = {}
        for i, (stamp, topic, msg) in enumerate(
                sorted(self.records, key=lambda r: r[0])):
            type_name = type(msg).__name__
            if type_name not in _TYPES:
                raise TypeError(f"cannot record {type_name} on {topic}")
            scalars, arrs = _split_msg(msg)
            index.append({"stamp": stamp, "topic": topic,
                          "type": type_name, "scalars": scalars})
            for k, a in arrs.items():
                arrays[f"r{i}.{k}"] = a
        meta = {"records": index, "config": config_json, "version": 2}
        arrays[_INDEX_KEY] = np.frombuffer(
            json.dumps(meta).encode(), np.uint8)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
        return len(index)


class TraceReplayer:
    """Load a bag and re-publish it."""

    def __init__(self, path: str):
        self.path = path
        with np.load(path) as z:
            meta = json.loads(bytes(z[_INDEX_KEY].tobytes()).decode())
            if isinstance(meta, dict):              # v2 wrapper
                self.index = meta["records"]
                self.config_json: Optional[str] = meta.get("config")
            else:                                    # v1 bare list
                self.index = meta
                self.config_json = None
            self._arrays = {k: z[k] for k in z.files if k != _INDEX_KEY}

    def __len__(self) -> int:
        return len(self.index)

    def messages(self):
        """Yield (stamp, topic, message) in stamp order."""
        for i, rec in enumerate(self.index):
            prefix = f"r{i}."
            arrays = {k[len(prefix):]: a for k, a in self._arrays.items()
                      if k.startswith(prefix)}
            yield rec["stamp"], rec["topic"], _join_msg(
                rec["type"], rec["scalars"], arrays)

    def replay(self, bus: Bus, speed: Optional[float] = None,
               topic_map: Optional[Dict[str, str]] = None) -> int:
        """Publish every record. speed=None: as fast as possible;
        speed=1.0: original timing (relative stamps). Returns count."""
        pubs: Dict[str, Any] = {}
        t0: Optional[float] = None
        wall0 = time.monotonic()
        n = 0
        for stamp, topic, msg in self.messages():
            topic = (topic_map or {}).get(topic, topic)
            if speed is not None:
                if t0 is None:
                    t0 = stamp
                due = wall0 + (stamp - t0) / speed
                delay = due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
            if topic not in pubs:
                pubs[topic] = bus.publisher(topic)
            pubs[topic].publish(msg)
            n += 1
        return n
