"""Warm-restart compile tier: persistent XLA cache + AOT snapshots.

The recompile telemetry (obs/devprof.py) and the XLA cost ledger show
that every PROCESS restart re-pays full compilation: a supervisor
checkpoint-resume lands a mapper that spends its first minutes
compiling, not mapping — availability traded for a compile storm. This
module is the storage tier of the warm-restart path (the staged
warm-up state machine lives in resilience/warmup.py):

* **Persistent compilation cache** — JAX's on-disk cache wired through
  `launch_sim_stack` (`CompileCacheManager.enable()`), with a BOUNDED
  on-disk budget enforced by least-recently-used eviction
  (`evict_lru`). Corrupt or incompatible entries are XLA's problem to
  detect; ours is to never crash on them: enable failures degrade to
  plain recompile with a flight-recorder event, and zero-byte husks
  (a crash mid-write) are scrubbed before enabling.

* **AOT executable snapshots** — one serialized `jax.export` program
  per (function, captured signature): the same jitted-entry-point
  registry `analysis/compilebudget.py` and `obs/devprof.py` walk
  supplies the functions (`_ProfiledJit` forwards `lower`, so profiled
  stacks AOT-lower transparently), and the dispatch profiler's
  captured abstract signatures supply the shapes. The exported
  StableHLO program IS the traced-and-lowered computation, so a resume
  process deserializes it instead of RE-TRACING (the dominant warm
  cost: slam_step's trace+lower alone runs seconds), and its compiled
  binary lands in — and is later served from — the persistent cache,
  which together snapshot the executable portably across processes on
  every backend (raw `serialize_executable` payloads do not
  deserialize cross-process on XLA:CPU at all). Custom pytree nodes
  (SlamState, PoseGraph, ...) are registered for export serialization
  on demand and recorded in the snapshot so the loader can re-register
  them. Snapshots live under a compatibility FINGERPRINT directory —
  blake2b over (jax version, jaxlib version, backend platform,
  normalized config JSON) — so a snapshot can never be served into an
  incompatible process: a fingerprint mismatch is counted and DEGRADES
  to the persistent cache, then to cold compile, never crashes.

* **Warm dispatch pool** — `_WarmJit`, the devprof-wrapper idiom: a
  transparent pass-through installed over the module aliases of each
  snapshotted entry point that serves calls whose abstract signature
  matches a loaded snapshot DIRECTLY through the deserialized
  program's `call` (the identical lowered computation the jit path
  would run — bit-identity is pinned by tests and the restart bench's
  cold/warm grid hashes) and falls through to the wrapped function on
  any miss or error, dropping the offending entry. A warm-served call
  never grows the jit cache, so `jax_mapping_jit_recompiles_total`
  stays honest for AOT-loaded variants by construction.

Thread contract: counters and the wipe refcount mutate only under
`_lock` (declared in analysis/protection.py); file I/O and jax calls
run OUTSIDE it — the leaf-lock discipline. The `cache_wipe` FaultPlan
kind drives `wipe_hold`/`wipe_release`: windows compose by refcount
(the first window's clear must not re-enable a cache another still
holds wiped), and a wipe mid-mission leaves the stack on the plain
recompile path — degraded, never broken.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from jax_mapping.config import ColdStartConfig

#: Snapshot file format version; bump on layout change (old files then
#: count as incompatible and degrade, never crash).
_SNAPSHOT_VERSION = 1

#: Process-global warm-pool install guard (the devprof pattern):
#: module-attribute rebinding is process-wide, one pool at a time.
_INSTALL_LOCK = threading.Lock()
_installed_pool: Optional["WarmPool"] = None


def cache_fingerprint(config_json: Optional[str] = None) -> str:
    """Compatibility fingerprint for AOT snapshots: jax + jaxlib
    versions, backend platform, and the (normalized) config JSON — a
    serialized executable is only valid against the exact compiler,
    runtime and static-argument surface that produced it. Infra-only
    sections (obs, cold_start — both bit-inert) are normalized out so
    flipping telemetry does not orphan a snapshot set."""
    import jax
    import jaxlib
    cfg_part = ""
    if config_json is not None:
        from jax_mapping.config import (ColdStartConfig as _CS,
                                        ObsConfig, SlamConfig)
        try:
            cfg = SlamConfig.from_json(config_json)
            cfg_part = cfg.replace(obs=ObsConfig(),
                                   cold_start=_CS()).to_json()
        except (TypeError, ValueError, KeyError):
            cfg_part = config_json
    h = hashlib.blake2b(digest_size=8)
    for part in (jax.__version__, jaxlib.__version__,
                 jax.default_backend(), cfg_part):
        h.update(part.encode())
        h.update(b"\0")
    return h.hexdigest()


#: Process-global export-serialization registry guard: jax registers
#: custom pytree serialization once per process; double registration
#: under a different name is an error, so all paths funnel here.
_EXPORT_REG_LOCK = threading.Lock()
_export_registered: set = set()


def _register_export_type(qualname: str) -> None:
    """Register one custom pytree class (by `module.Class` qualname)
    for jax.export serialization, idempotently."""
    with _EXPORT_REG_LOCK:
        if qualname in _export_registered:
            return
    import importlib

    from jax import export as jexp
    modname, clsname = qualname.rsplit(".", 1)
    cls = getattr(importlib.import_module(modname), clsname)
    try:
        jexp.register_namedtuple_serialization(cls,
                                               serialized_name=qualname)
    except ValueError:
        # Already registered (an earlier load, another manager): jax
        # keeps one process-global registry; ours just mirrors it.
        pass
    with _EXPORT_REG_LOCK:
        _export_registered.add(qualname)


def _serialize_with_registrations(exported) -> Tuple[bytes, list]:
    """`exported.serialize()` with on-demand registration of the custom
    pytree nodes it trips over (SlamState, PoseGraph, ...). Returns
    (blob, qualnames) where qualnames is every registration this
    process has made — a SUPERSET of what this blob needs, recorded in
    the snapshot so the loading process can re-register before
    deserializing."""
    import re
    for _ in range(32):
        try:
            blob = exported.serialize()
            break
        except ValueError as e:
            m = re.search(r"unregistered type `<class '([\w\.]+)'>`",
                          str(e))
            if m is None:
                raise
            _register_export_type(m.group(1))
    else:
        raise RuntimeError(
            "export serialization registration did not converge")
    with _EXPORT_REG_LOCK:
        regs = sorted(_export_registered)
    return blob, regs


def _has_array_leaf(x: Any) -> bool:
    """Whether an abstracted argument contains any ShapeDtypeStruct-like
    leaf — the static-vs-dynamic heuristic for calling a `Compiled`
    (which takes only the dynamic arguments). Misclassification is
    caught empirically at snapshot time (`_call_mode`)."""
    import jax
    found = []

    def look(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            found.append(True)
        return leaf

    jax.tree_util.tree_map(look, x)
    return bool(found)


def materialize_zeros(sig: tuple) -> Tuple[tuple, dict]:
    """(args, kwargs) with every abstract array leaf replaced by a
    concrete zeros array — the pre-warm input: calling an entry point
    with these drives exactly the compile (or cache hit) the captured
    live signature would."""
    import jax
    import jax.numpy as jnp

    def concretize(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jnp.zeros(tuple(x.shape), x.dtype)
        return x

    args, kwargs = jax.tree_util.tree_map(concretize, sig)
    return args, kwargs


class _WarmJit:
    """Transparent warm-dispatch wrapper for one snapshotted entry
    point: calls whose abstract signature matches a loaded AOT
    executable are served from it (the identical compiled binary the
    jit path would run); everything else falls through to the wrapped
    function. Forwards `_cache_size`/`lower`/`__name__` so registry
    walks, compile budgets, profilers and AOT lowering see through it
    (the `_ProfiledJit` contract)."""

    __slots__ = ("_fn", "_pool", "_name")

    def __init__(self, fn, pool: "WarmPool", name: str):
        self._fn = fn
        self._pool = pool
        self._name = name

    def __call__(self, *args, **kwargs):
        entry = self._pool.lookup(self._name, args, kwargs)
        if entry is not None:
            compiled, mode, dyn_idx, dyn_kw, key = entry
            try:
                if mode == "dyn":
                    return compiled(
                        *[args[i] for i in dyn_idx if i < len(args)],
                        **{k: kwargs[k] for k in dyn_kw if k in kwargs})
                return compiled(*args, **kwargs)
            except Exception:                       # noqa: BLE001
                # The ladder's bottom rung: a warm executable that will
                # not take this call (aval/sharding drift) is dropped
                # and the call recompiles through the ordinary path.
                self._pool.drop(self._name, key)
        return self._fn(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_fn"), item)

    # The PR 10 gotcha: `__module__`/`__doc__` land in the class dict
    # at class creation, so instance lookup never reaches __getattr__ —
    # forward them explicitly or compilebudget's owner-qualified names
    # corrupt while the pool is installed.
    @property
    def __module__(self):
        return getattr(self._fn, "__module__", None)

    @property
    def __doc__(self):
        return getattr(self._fn, "__doc__", None)

    def __repr__(self) -> str:
        return f"<warm {self._name}>"


class WarmPool:
    """Loaded AOT executables keyed (function name, signature key),
    plus the module-rebinding install/uninstall that puts `_WarmJit`
    wrappers over exactly the snapshotted entry points."""

    def __init__(self):
        self._lock = threading.Lock()
        #: {fn_name: {sig_key: (compiled, mode, dyn_idx, dyn_kw)}}
        self._entries: Dict[str, Dict[str, tuple]] = {}
        self.n_served = 0
        self.n_fallthrough = 0
        self.n_dropped = 0
        self._bindings: List[Tuple[_WarmJit, list]] = []
        self.installed = False

    def add(self, fn_name: str, sig_key: str, compiled, mode: str,
            dyn_idx: tuple, dyn_kw: tuple) -> None:
        with self._lock:
            self._entries.setdefault(fn_name, {})[sig_key] = \
                (compiled, mode, dyn_idx, dyn_kw)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def keys_for(self, fn_name: str) -> set:
        with self._lock:
            return set(self._entries.get(fn_name, ()))

    def entry(self, fn_name: str, sig_key: str):
        """(compiled, mode, dyn_idx, dyn_kw) by exact key, or None —
        the staged warm-up executes each pooled entry once on zeros so
        its compile cost (a cache hit, normally) is paid during the
        warm-up, never by the first live call."""
        with self._lock:
            return self._entries.get(fn_name, {}).get(sig_key)

    def n_entries(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._entries.values())

    def lookup(self, fn_name: str, args: tuple, kwargs: dict):
        """The per-call match: abstract the live arguments exactly the
        way devprof captured them and look the key up. Returns
        (compiled, mode, dyn_idx, dyn_kw, key) or None."""
        with self._lock:
            if not self._entries.get(fn_name):
                return None
        from jax_mapping.obs.devprof import abstract_signature
        try:
            key = repr(abstract_signature(args, kwargs))
        except Exception:                           # noqa: BLE001
            return None
        with self._lock:
            # Re-resolve through self._entries (NOT a dict captured in
            # the first section): a cache_wipe's clear() swaps the
            # table while the key is computed, and serving from the
            # orphaned dict would misreport the wipe as survivable
            # warm state.
            ent = self._entries.get(fn_name, {}).get(key)
            if ent is None:
                self.n_fallthrough += 1
                return None
            self.n_served += 1
            return ent + (key,)

    def drop(self, fn_name: str, sig_key: str) -> None:
        with self._lock:
            self._entries.get(fn_name, {}).pop(sig_key, None)
            self.n_dropped += 1

    def clear(self) -> None:
        """Drop every entry (cache_wipe); installed wrappers stay and
        simply fall through from now on."""
        with self._lock:
            self._entries = {}

    def stats(self) -> dict:
        with self._lock:
            return {"n_entries": sum(len(v)
                                     for v in self._entries.values()),
                    "n_served": self.n_served,
                    "n_fallthrough": self.n_fallthrough,
                    "n_dropped": self.n_dropped,
                    "installed": self.installed}

    # -- module rebinding (the devprof install idiom) -----------------------

    def install(self, prefix: str = "jax_mapping") -> int:
        """Wrap every importable alias of each pooled entry point;
        returns how many functions were wrapped. Installs OVER a
        profiler wrapper transparently (the profiler then times warm
        dispatches too); a second live pool is refused."""
        global _installed_pool
        with _INSTALL_LOCK:
            if _installed_pool is not None and _installed_pool is not self:
                raise RuntimeError(
                    "another WarmPool is installed — uninstall it first "
                    "(wrappers are process-global)")
            with self._lock:
                wanted = {n for n, sigs in self._entries.items() if sigs}
            targets: Dict[int, Tuple[object, list]] = {}
            for mod_name in sorted(sys.modules):
                mod = sys.modules[mod_name]
                if mod is None or not mod_name.startswith(prefix):
                    continue
                for attr in sorted(vars(mod)):
                    fn = vars(mod)[attr]
                    if isinstance(fn, _WarmJit):
                        continue
                    cache_size = getattr(fn, "_cache_size", None)
                    if not callable(cache_size) or not callable(fn):
                        continue
                    name = qualified_name(fn, mod_name, attr, prefix)
                    if name not in wanted:
                        continue
                    ent = targets.setdefault(id(fn), (fn, []))
                    ent[1].append((mod, attr, name))
            for fn, sites in targets.values():
                wrapper = _WarmJit(fn, self, sites[0][2])
                for mod, attr, _ in sites:
                    setattr(mod, attr, wrapper)
                self._bindings.append((wrapper,
                                       [(m, a) for m, a, _ in sites]))
            _installed_pool = self
            with self._lock:
                self.installed = True
            return len(targets)

    def uninstall(self) -> None:
        """Remove our wrappers from every site, UNWRAPPING from inside
        wrapper chains: a profiler installed after us holds the site as
        `_ProfiledJit(_WarmJit(fn))` (and vice versa after a staged
        restart), and a direct-match-only restore would either strand
        our wrapper inside the chain or restore nothing — the shutdown
        leak that leaves a dead wrapper bound at module scope.
        Idempotent."""
        global _installed_pool
        with _INSTALL_LOCK:
            for wrapper, sites in self._bindings:
                for mod, attr in sites:
                    cur = vars(mod).get(attr)
                    if cur is wrapper:
                        setattr(mod, attr, wrapper._fn)
                        continue
                    node = cur
                    while hasattr(node, "_fn"):
                        if node._fn is wrapper:
                            # Splice ourselves out of the chain; _fn is
                            # a __slots__ attribute on every wrapper
                            # class in this repo.
                            object.__setattr__(node, "_fn", wrapper._fn)
                            break
                        node = node._fn
            self._bindings = []
            if _installed_pool is self:
                _installed_pool = None
            with self._lock:
                self.installed = False


def qualified_name(fn, mod_name: str, attr: str, prefix: str) -> str:
    """The compilebudget naming contract (defining module + name,
    stable across from-import aliases) — ONE definition shared with the
    snapshot filenames so a pool entry always matches its registry
    walk."""
    owner = getattr(fn, "__module__", mod_name) or mod_name
    name = getattr(fn, "__name__", attr) or attr
    if not owner.startswith(prefix):
        owner = mod_name
    return f"{owner}.{name}"


def resolve_entry_point(name: str, prefix: str = "jax_mapping"):
    """The RAW jitted function for a registry-qualified name, unwrapping
    any profiler/warm wrappers (`._fn` chains) — pre-warm calls and AOT
    lowering must reach the underlying jit, not count as profiled
    dispatches."""
    for mod_name in sorted(sys.modules):
        mod = sys.modules[mod_name]
        if mod is None or not mod_name.startswith(prefix):
            continue
        for attr in sorted(vars(mod)):
            fn = vars(mod)[attr]
            if not callable(getattr(fn, "_cache_size", None)):
                continue
            if qualified_name(fn, mod_name, attr, prefix) == name:
                while hasattr(fn, "_fn"):
                    fn = fn._fn
                return fn
    return None


class CompileCacheManager:
    """One stack's handle on the warm-restart storage tier."""

    def __init__(self, cfg: ColdStartConfig, root: str,
                 config_json: Optional[str] = None):
        self.cfg = cfg
        self.root = root
        self.config_json = config_json
        self._lock = threading.Lock()
        self._wipe_refs = 0
        self._counts: Dict[str, int] = {}
        self.enabled = False
        self.fingerprint: Optional[str] = None
        self.pool = WarmPool()

    # -- paths ----------------------------------------------------------------

    @property
    def xla_dir(self) -> str:
        return os.path.join(self.root, "xla")

    def aot_dir(self, fingerprint: Optional[str] = None) -> str:
        fp = fingerprint or self.fingerprint or "unknown"
        return os.path.join(self.root, "aot", fp)

    def _count(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + by

    # -- the persistent compilation cache ------------------------------------

    def enable(self) -> bool:
        """Point JAX's persistent compilation cache at our XLA dir
        (min-compile-time and min-entry-size floors dropped so the
        tiny-config scenario's entries persist too). Failures — an old
        jax without the flags, an unwritable volume — degrade to plain
        recompile with a flight-recorder event; never raise."""
        with self._lock:
            wiped = self._wipe_refs > 0
        if wiped:
            return False
        try:
            self.fingerprint = cache_fingerprint(self.config_json)
            os.makedirs(self.xla_dir, exist_ok=True)
            self._scrub_husks(self.xla_dir)
            import jax
            jax.config.update("jax_compilation_cache_dir", self.xla_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception as e:                      # noqa: BLE001
            self._count("enable_failed")
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("compile_cache_degraded",
                                   stage="enable", error=type(e).__name__)
            self.enabled = False
            return False
        self.enabled = True
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("compile_cache_enabled",
                               fingerprint=self.fingerprint)
        return True

    def disable(self) -> None:
        """Detach the process-global cache dir (Stack.shutdown: the next
        stack owns the config)."""
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:                           # noqa: BLE001
            pass
        self.enabled = False

    def _scrub_husks(self, d: str) -> int:
        """Delete zero-byte cache files (a crash mid-write leaves them;
        XLA treats a truncated entry as an error worth warning about on
        every hit) — the cheap structural scrub; content corruption is
        caught per-entry at load/deserialize time and degrades."""
        n = 0
        for base, _dirs, files in os.walk(d):
            for f in files:
                p = os.path.join(base, f)
                try:
                    if os.path.getsize(p) == 0:
                        os.unlink(p)
                        n += 1
                except OSError:
                    continue
        if n:
            self._count("husks_scrubbed", n)
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("compile_cache_scrub", n=n)
        return n

    def evict_lru(self) -> Tuple[int, int]:
        """Enforce `max_cache_bytes` over the cache root: files beyond
        the budget go oldest-mtime-first. Returns (n_evicted,
        bytes_freed); errors skip the file (a racing evictor or a
        permissions oddity must not crash a restart path)."""
        budget = self.cfg.max_cache_bytes
        entries = []
        total = 0
        for base, _dirs, files in os.walk(self.root):
            for f in files:
                p = os.path.join(base, f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
        if total <= budget:
            return 0, 0
        entries.sort()
        n = freed = 0
        for _mt, size, p in entries:
            if total - freed <= budget:
                break
            try:
                os.unlink(p)
            except OSError:
                continue
            n += 1
            freed += size
        if n:
            self._count("lru_evicted", n)
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("compile_cache_evict", n=n,
                                   bytes=freed)
        return n, freed

    # -- AOT snapshots --------------------------------------------------------

    def save_aot(self, signatures: Dict[str, List[tuple]],
                 resolve: Optional[Callable[[str], Any]] = None) -> dict:
        """Serialize one compiled executable per (function, captured
        signature) into the fingerprint directory. `signatures` is the
        dispatch profiler's capture (`DispatchProfiler.signatures()`);
        `resolve` maps a qualified name to its callable (default: the
        registry walk). Per-entry failures are counted and skipped —
        a snapshot pass degrades, it never takes the mission down."""
        report = {"n_saved": 0, "n_failed": 0, "n_uncallable": 0,
                  "names": []}
        if not self.cfg.aot_snapshots:
            return report
        with self._lock:
            wiped = self._wipe_refs > 0
        if wiped:
            return report
        try:
            from jax import export as _jexp                 # noqa: F401
        except Exception:                           # noqa: BLE001
            self._count("aot_unavailable")
            return report
        if self.fingerprint is None:
            self.fingerprint = cache_fingerprint(self.config_json)
        d = self.aot_dir()
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            report["n_failed"] += 1
            return report
        for fn_name in sorted(signatures):
            fn = (resolve(fn_name) if resolve is not None
                  else resolve_entry_point(fn_name))
            if fn is None or not hasattr(fn, "lower"):
                report["n_failed"] += len(signatures[fn_name])
                continue
            for i, sig in enumerate(signatures[fn_name]):
                try:
                    entry = self._build_snapshot(fn, fn_name, sig)
                except Exception:                   # noqa: BLE001
                    report["n_failed"] += 1
                    self._count("aot_save_failed")
                    continue
                if entry is None:
                    report["n_uncallable"] += 1
                    continue
                safe = fn_name.replace("/", "_")
                path = os.path.join(d, f"{safe}__{i:02d}.aot")
                tmp = path + ".tmp"
                try:
                    with open(tmp, "wb") as f:
                        f.write(pickle.dumps(entry))
                    os.replace(tmp, path)
                except (OSError, pickle.PicklingError):
                    report["n_failed"] += 1
                    self._count("aot_save_failed")
                    continue
                report["n_saved"] += 1
                report["names"].append(fn_name)
        self._count("aot_saved", report["n_saved"])
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("aot_snapshot_save",
                               n=report["n_saved"],
                               n_failed=report["n_failed"])
        self.evict_lru()
        return report

    def _build_snapshot(self, fn, fn_name: str, sig: tuple):
        """One snapshot dict, or None when the exported program's
        calling convention could not be established (neither
        dynamic-only nor full-argument calls work — skip rather than
        snapshot something the warm path can never serve). The
        validation call doubles as cache population: the exported
        program's compiled binary lands in the persistent cache NOW, so
        a resume process's first warm-served call is a cache hit."""
        from jax import export as jexp
        from jax_mapping.obs.devprof import abstract_signature
        args, kwargs = sig
        exported = jexp.export(fn)(*args, **kwargs)
        blob, regs = _serialize_with_registrations(exported)
        zargs, zkwargs = materialize_zeros(sig)
        dyn_idx = tuple(i for i, a in enumerate(args)
                        if _has_array_leaf(a))
        dyn_kw = tuple(k for k, v in sorted(kwargs.items())
                       if _has_array_leaf(v))
        mode = None
        try:
            exported.call(*[zargs[i] for i in dyn_idx],
                          **{k: zkwargs[k] for k in dyn_kw})
            mode = "dyn"
        except Exception:                           # noqa: BLE001
            try:
                exported.call(*zargs, **zkwargs)
                mode = "full"
            except Exception:                       # noqa: BLE001
                return None
        return {
            "version": _SNAPSHOT_VERSION,
            "fn": fn_name,
            "sig_key": repr(abstract_signature(args, kwargs)),
            "sig": sig,
            "blob": bytes(blob),
            "regs": regs,
            "mode": mode,
            "dyn_idx": dyn_idx,
            "dyn_kw": dyn_kw,
        }

    def load_aot(self) -> dict:
        """Walk the fingerprint directory, deserialize every intact
        snapshot into the warm pool, and return the prewarm manifest:
        {"pool_names": [...], "signatures": {fn: [sig, ...]},
        counters...}. Every failure mode DEGRADES: an unpicklable or
        wrong-version file counts corrupt; an executable the backend
        will not deserialize (XLA:CPU cross-process) degrades to its
        captured signature so the warm-up can pre-warm through the
        persistent cache; other fingerprints present are counted as
        mismatches and never read."""
        report = {"n_loaded": 0, "n_corrupt": 0, "n_degraded": 0,
                  "n_fingerprint_mismatch": 0,
                  "signatures": {}, "pool_names": []}
        if not self.cfg.aot_snapshots:
            return report
        if self.fingerprint is None:
            self.fingerprint = cache_fingerprint(self.config_json)
        aot_root = os.path.join(self.root, "aot")
        try:
            siblings = sorted(os.listdir(aot_root))
        except OSError:
            siblings = []
        for fp in siblings:
            if fp != self.fingerprint and \
                    os.path.isdir(os.path.join(aot_root, fp)):
                report["n_fingerprint_mismatch"] += 1
        d = self.aot_dir()
        try:
            files = sorted(f for f in os.listdir(d)
                           if f.endswith(".aot"))
        except OSError:
            files = []
        jexp = None
        try:
            from jax import export as jexp
        except Exception:                           # noqa: BLE001
            pass
        for f in files:
            path = os.path.join(d, f)
            try:
                with open(path, "rb") as fh:
                    entry = pickle.loads(fh.read())
                if not isinstance(entry, dict) or \
                        entry.get("version") != _SNAPSHOT_VERSION:
                    raise ValueError("bad snapshot layout")
                fn_name = entry["fn"]
                sig = entry["sig"]
            except Exception:                       # noqa: BLE001
                report["n_corrupt"] += 1
                continue
            try:
                # LRU recency: snapshots are written once and READ on
                # every warm restart — without a touch, eviction would
                # reap the hottest tier first (XLA entries written
                # later always look fresher by mtime).
                os.utime(path)
            except OSError:
                pass
            report["signatures"].setdefault(fn_name, []).append(sig)
            if jexp is None:
                report["n_degraded"] += 1
                continue
            try:
                for qual in entry.get("regs", ()):
                    _register_export_type(qual)
                exported = jexp.deserialize(bytearray(entry["blob"]))
                compiled = exported.call
            except Exception:                       # noqa: BLE001
                # Any drift the fingerprint missed (an incompatible
                # export version, a vanished pytree class): the
                # signature still pre-warms through the persistent
                # cache — the ladder's next rung.
                report["n_degraded"] += 1
                continue
            self.pool.add(fn_name, entry["sig_key"], compiled,
                          entry["mode"], tuple(entry["dyn_idx"]),
                          tuple(entry["dyn_kw"]))
            report["n_loaded"] += 1
            if fn_name not in report["pool_names"]:
                report["pool_names"].append(fn_name)
        with self._lock:
            for k in ("n_loaded", "n_corrupt", "n_degraded",
                      "n_fingerprint_mismatch"):
                self._counts["aot_" + k] = \
                    self._counts.get("aot_" + k, 0) + report[k]
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record(
            "aot_snapshot_load", n=report["n_loaded"],
            n_corrupt=report["n_corrupt"],
            n_degraded=report["n_degraded"],
            n_fingerprint_mismatch=report["n_fingerprint_mismatch"])
        return report

    # -- cache_wipe fault boundary -------------------------------------------

    def wipe_hold(self) -> None:
        """One `cache_wipe` window opens: delete everything under the
        cache root and suppress cache writes while ANY window holds
        (refcounted — the FaultPlan composition doctrine). The stack
        keeps running on plain recompile."""
        with self._lock:
            self._wipe_refs += 1
            self._counts["wipes"] = self._counts.get("wipes", 0) + 1
        self.disable()
        n = 0
        for base, _dirs, files in os.walk(self.root, topdown=False):
            for f in files:
                try:
                    os.unlink(os.path.join(base, f))
                    n += 1
                except OSError:
                    continue
        # Loaded warm entries are dropped too: their files are gone,
        # and serving a wiped executable would misreport the wipe as
        # survivable warm state.
        self.pool.clear()
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("cache_wipe", n_files=n)

    def wipe_release(self) -> None:
        """One window clears; the LAST one out re-enables the (now
        empty) cache so subsequent compiles repopulate it."""
        with self._lock:
            self._wipe_refs = max(0, self._wipe_refs - 1)
            refs = self._wipe_refs
        if refs == 0:
            self.enable()

    # -- export ---------------------------------------------------------------

    def disk_usage_bytes(self) -> int:
        total = 0
        for base, _dirs, files in os.walk(self.root):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(base, f))
                except OSError:
                    continue
        return total

    def status(self) -> dict:
        """The /status `cold_start` export (+ test assertion surface)."""
        with self._lock:
            counts = dict(self._counts)
            refs = self._wipe_refs
        return {"enabled": self.enabled,
                "fingerprint": self.fingerprint,
                "wipe_refs": refs,
                "counts": counts,
                "pool": self.pool.stats()}
