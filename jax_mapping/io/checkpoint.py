"""Checkpoint/resume for device state pytrees.

The reference loses its whole map on restart — slam_toolbox's serialization
API exists but is never invoked (`enable_interactive_mode: true` at
`/root/reference/server/thymio_project/config/slam_config.yaml:32`,
SURVEY.md §5 "Checkpoint / resume: none"). Here any pytree of arrays
(SlamState, FleetState, raw grids) round-trips exactly through one `.npz`
file, with the config JSON embedded so a resume can detect shape drift.

Plain npz rather than orbax: single-host state of a few hundred MB max,
no need for async/multi-host sharded checkpointing machinery — and the file
is inspectable with numpy alone. The layout is flatten-with-paths, so any
NamedTuple nesting (SlamState.graph.poses, ...) keys stably.
"""

from __future__ import annotations

import io
import json
import os
import threading
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_META_KEY = "__jax_mapping_meta__"

#: Fallback-slot load counters (ISSUE 12 satellite): which generation
#: `load_checkpoint_with_fallback` actually chose — today a silent
#: `.prev` rescue is indistinguishable from a clean primary load. The
#: HTTP plane renders these as
#: `jax_mapping_checkpoint_fallback_total{slot=...}`; all three slots
#: always report (an absent label and a zero counter mean different
#: things to a rate() query).
_FALLBACK_SLOTS = ("primary", "prev", "generation")
_fallback_lock = threading.Lock()
_fallback_counts: Dict[str, int] = {s: 0 for s in _FALLBACK_SLOTS}


def fallback_slot(path: str, used_path: str) -> str:
    """Which retention slot `used_path` is for checkpoint `path`:
    primary, the rotated `.prev` last-good, or a numbered
    `.genNNNNNN` generation."""
    if used_path == path:
        return "primary"
    if used_path == previous_checkpoint_path(path):
        return "prev"
    return "generation"


def fallback_counts() -> Dict[str, int]:
    """Snapshot of the per-slot fallback-load counters."""
    with _fallback_lock:
        return dict(_fallback_counts)


class CheckpointCorrupt(ValueError):
    """A checkpoint file exists but cannot be trusted: truncated zip,
    unreadable meta, or a per-leaf CRC32 mismatch (bit rot, power loss
    despite the atomic rename, a corrupted sidecar copy). Subclasses
    ValueError so every existing load-error handler still catches it;
    the supervisor's auto-resume catches it SPECIFICALLY and falls back
    to the rotated last-good file (`previous_checkpoint_path`)."""


def previous_checkpoint_path(path: str) -> str:
    """Rotation slot for the last-good checkpoint: `save_checkpoint`
    moves the existing file here before installing the new one, so a
    save that lands corrupt (or corrupts later on disk) always leaves
    one older intact generation to fall back to."""
    root, ext = os.path.splitext(path)
    return root + ".prev" + (ext or ".npz")


def generation_paths(path: str) -> list:
    """Numbered retained generations for checkpoint `path`, OLDEST
    first (index order). Only exist when saves run with
    `retain_generations` > 2 — the lifelong-session deep history behind
    the current + `.prev` pair."""
    import glob as _glob
    import re
    root, ext = os.path.splitext(path)
    ext = ext or ".npz"
    pat = re.compile(re.escape(root) + r"\.gen(\d{6})" + re.escape(ext)
                     + r"$")
    out = []
    for p in sorted(_glob.glob(root + ".gen??????" + ext)):
        m = pat.match(p)
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def _generation_path(path: str, idx: int) -> str:
    root, ext = os.path.splitext(path)
    return f"{root}.gen{idx:06d}" + (ext or ".npz")


def _next_generation_path(path: str) -> str:
    import re
    gens = generation_paths(path)
    if not gens:
        return _generation_path(path, 0)
    last = int(re.search(r"\.gen(\d{6})", gens[-1]).group(1))
    return _generation_path(path, last + 1)


def _gc_generations(path: str, retain_generations: int) -> None:
    """Delete numbered generations beyond the retention budget,
    corruption-safely: the budget counts current + `.prev` + numbered
    files, and when BOTH rotation slots are rotten the newest intact
    numbered generation is spared regardless of budget — GC must never
    delete the only resume source a corrupted pair would fall back
    to."""
    gens = generation_paths(path)
    budget = max(0, retain_generations - 2)
    doomed = gens[:len(gens) - budget] if budget else list(gens)
    if not doomed:
        return
    if not (_looks_intact(path)
            or _looks_intact(previous_checkpoint_path(path))):
        for g in reversed(gens):
            if _looks_intact(g):
                doomed = [d for d in doomed if d != g]
                break
    for d in doomed:
        try:
            os.unlink(d)
        except OSError:
            pass                         # a racing GC already took it


def _path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(p.name)
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return ".".join(parts) or "value"


def _leaf_crc(arr: np.ndarray) -> int:
    """CRC32 over the raw leaf bytes (C-contiguous), the integrity
    check zip-member CRCs cannot replace: numpy's zip reader surfaces a
    bad member as an opaque zlib/zipfile error mid-array, and a
    truncated-but-valid-zip (partial sidecar copy) passes zipfile
    entirely."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def save_checkpoint(path: str, state: Any,
                    config_json: Optional[str] = None,
                    retain_generations: int = 2) -> None:
    """Write `state` (any pytree of arrays/scalars) to `path` atomically.

    Meta carries a per-leaf CRC32 (`load_checkpoint` verifies) and any
    existing file at `path` rotates to `previous_checkpoint_path(path)`
    first — corruption on load degrades to the previous generation
    instead of losing the map.

    `retain_generations` bounds the on-disk history for lifelong
    sessions: 2 (default) is the historical current + `.prev` pair
    exactly; K > 2 additionally rotates the outgoing `.prev` into a
    numbered `.genNNNNNN` slot and GCs numbered generations oldest-
    first so at most K generations remain — corruption-safely (the
    newest intact generation is never deleted, and only structurally
    intact files rotate; see `_gc_generations`)."""
    if retain_generations < 2:
        raise ValueError(
            f"retain_generations={retain_generations} < 2: the current "
            "+ .prev last-good pair is the corruption-fallback floor")
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    arrays = {}
    keys = []
    crcs = {}
    for kpath, leaf in leaves_with_paths:
        key = _path_str(kpath)
        assert key not in arrays, f"duplicate checkpoint key {key}"
        arrays[key] = np.asarray(leaf)
        crcs[key] = _leaf_crc(arrays[key])
        keys.append(key)
    meta = {
        "keys": keys,                       # leaf order for exact rebuild
        "treedef": str(treedef),            # debugging aid only
        "config": config_json,
        "crc32": crcs,
        "version": 1,
    }
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    if os.path.exists(path) and _looks_intact(path):
        # Rotate ONLY a structurally sound file into the last-good slot:
        # rotating a truncated/corrupted primary would evict the genuine
        # last-good generation and leave nothing to fall back to. (Cheap
        # check — zip directory + meta member, no full-array CRC; a
        # bit-rotted-but-well-formed file can still slip through, which
        # load's per-leaf CRC then catches at resume time.)
        prev = previous_checkpoint_path(path)
        if retain_generations > 2 and os.path.exists(prev) \
                and _looks_intact(prev):
            # Deep retention: the outgoing last-good generation becomes
            # a numbered slot instead of being overwritten.
            os.replace(prev, _next_generation_path(path))
        os.replace(path, prev)
    os.replace(tmp, path)                   # crash-safe swap
    if retain_generations > 2:
        _gc_generations(path, retain_generations)
    # Flight-recorder transition (obs/): saves are load-bearing — a
    # postmortem of a bad resume starts with "which generation was
    # current when". Basename only: absolute tmp dirs would break the
    # two-same-seed-runs stream-identity contract.
    from jax_mapping.obs.recorder import flight_recorder
    flight_recorder.record("checkpoint_save",
                           name=os.path.basename(path))


def _looks_intact(path: str) -> bool:
    """Structural sanity for rotation: readable zip with a meta member."""
    try:
        with np.load(path) as z:
            json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return True
    except Exception:                        # noqa: BLE001
        return False


def load_checkpoint(path: str, like: Any
                    ) -> Tuple[Any, Optional[str]]:
    """Read a checkpoint into the structure of `like` (a template pytree,
    e.g. `init_state(cfg)`), returning (state, config_json).

    Leaf dtypes follow the template (so restored state is jit-compatible
    with the running program); a shape mismatch raises with the offending
    key named. A file that cannot be read or whose per-leaf CRC32 does
    not match raises `CheckpointCorrupt` (a ValueError) instead of a raw
    zipfile/KeyError — callers with a fallback generation (the
    supervisor) branch on it.
    """
    if not os.path.exists(path):
        # Missing-file stays FileNotFoundError (callers distinguish
        # "no checkpoint yet" from "checkpoint rotted").
        raise FileNotFoundError(path)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
            data = {k: z[k] for k in meta["keys"]}
    except (OSError, KeyError, ValueError, json.JSONDecodeError,
            zipfile.BadZipFile, zlib.error) as e:
        # Raw zipfile/KeyError escapes are exactly what the corruption
        # contract forbids: a truncated npz, a missing meta member, or
        # a zlib stream error all mean the same thing to a caller.
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable ({type(e).__name__}: {e})"
        ) from e
    crcs = meta.get("crc32")
    if crcs is not None:
        bad = [k for k in meta["keys"]
               if k in crcs and _leaf_crc(data[k]) != crcs[k]]
        if bad:
            raise CheckpointCorrupt(
                f"checkpoint {path} failed CRC32 verification on "
                f"leaves {bad} — corrupted on disk")

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    if len(leaves_with_paths) != len(meta["keys"]):
        raise ValueError(
            f"checkpoint has {len(meta['keys'])} leaves, template has "
            f"{len(leaves_with_paths)} — config/shape drift?")
    new_leaves = []
    for kpath, leaf in leaves_with_paths:
        key = _path_str(kpath)
        if key not in data:
            raise ValueError(f"checkpoint missing leaf {key!r}")
        arr = data[key]
        tmpl = np.asarray(leaf)
        if arr.shape != tmpl.shape:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {arr.shape} != template "
                f"{tmpl.shape} — was the config changed?")
        new_leaves.append(jax.numpy.asarray(arr.astype(tmpl.dtype)))
    from jax_mapping.obs.recorder import flight_recorder
    flight_recorder.record("checkpoint_load",
                           name=os.path.basename(path))
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta["config"]


def load_checkpoint_with_fallback(path: str, like: Any
                                  ) -> Tuple[Any, Optional[str], str]:
    """`load_checkpoint`, degrading to the rotated last-good generation.

    Returns (state, config_json, used_path). A corrupt or missing
    `path` falls back to `previous_checkpoint_path(path)`, then down
    the numbered retained generations newest-first; only when EVERY
    generation fails does the error propagate (the primary's error:
    CheckpointCorrupt for corruption, FileNotFoundError when no file
    exists). THE
    resume path for the supervisor's restart-from-checkpoint: a mapper
    crash right after a corrupted save must still resume from the
    previous map rather than restart blank."""
    candidates = [path, previous_checkpoint_path(path)]
    # Deep retention (retain_generations > 2 saves): numbered
    # generations extend the fallback chain, newest first.
    candidates += list(reversed(generation_paths(path)))
    first_err: Optional[Exception] = None
    for p in candidates:
        try:
            state, cfg_json = load_checkpoint(p, like)
        except (CheckpointCorrupt, FileNotFoundError) as e:
            if first_err is None:
                first_err = e
            continue
        # Which generation actually resumed (ISSUE 12 satellite): the
        # flight-recorder event + per-slot counter make a silent .prev
        # or .genNNNNNN rescue operator-visible — a fallback load means
        # a newer generation rotted, which a postmortem must know.
        slot = fallback_slot(path, p)
        with _fallback_lock:
            _fallback_counts[slot] += 1
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("checkpoint_fallback", slot=slot,
                               name=os.path.basename(p),
                               fell_back=slot != "primary")
        return state, cfg_json, p
    raise first_err


def voxel_sidecar_path(path: str) -> str:
    """Sidecar file for the 3D voxel map next to a 2D checkpoint: the
    grid ships separately so pre-3D checkpoints stay loadable and
    2D-only stacks never pay the 3D bytes."""
    root, ext = os.path.splitext(path)
    return root + ".voxel" + (ext or ".npz")


# Sentinel leaf marking a file as a voxel sidecar: checkpoint "x"'s
# sidecar shares its filename with a hypothetical checkpoint named
# "x.voxel", and without the marker a save could silently clobber one
# with the other (code-review r4).
_VOXEL_SENTINEL = "voxel_sidecar_marker"


def save_voxel_sidecar(path: str, grid: Any,
                       config_json: Optional[str] = None) -> str:
    """Write the 3D grid as `path`'s sidecar; returns the sidecar path.

    Refuses to overwrite an existing file that is NOT a voxel sidecar
    (the name-collision case above) — silent 2D-checkpoint data loss is
    worse than an error."""
    vp = voxel_sidecar_path(path)
    if os.path.exists(vp) and not _is_voxel_sidecar(vp):
        raise ValueError(
            f"{vp} exists and is not a voxel sidecar (a checkpoint named "
            f"with the reserved '.voxel' suffix?); refusing to overwrite")
    save_checkpoint(vp, {"grid": grid, _VOXEL_SENTINEL: np.int8(1)},
                    config_json=config_json)
    return vp


def load_voxel_sidecar(path: str, template_grid: Any,
                       running_config_json: Optional[str] = None) -> Any:
    """Load `path`'s 3D sidecar grid, or None when no sidecar exists.

    Raises ValueError — with a message naming the problem — on a
    non-sidecar file at the sidecar path, shape drift, or config drift
    (semantic comparison, config.configs_equivalent). ONE validation
    path for every consumer (demo --resume, HTTP /load)."""
    vp = voxel_sidecar_path(path)
    if not os.path.exists(vp):
        return None
    if not _is_voxel_sidecar(vp):
        raise ValueError(
            f"{vp} is not a voxel sidecar (name collision with a "
            f"checkpoint named '.voxel'?); refusing to load")
    state, cfg_json = load_checkpoint(
        vp, {"grid": template_grid, _VOXEL_SENTINEL: np.int8(0)})
    if cfg_json is not None and running_config_json is not None:
        from jax_mapping.config import configs_equivalent
        if not configs_equivalent(cfg_json, running_config_json):
            raise ValueError(
                "voxel sidecar config differs from the running config")
    return state["grid"]


def prior_sidecar_path(path: str) -> str:
    """Sidecar for an imported map prior (mapper.seed_map_prior) next to
    a 2D checkpoint. Ships separately so checkpoints without a prior
    stay byte-identical to before, and because the prior must survive a
    resume: closure re-fusions rebuild the grid from empty + rings, and
    a restored session without its prior would erase the imported map at
    the first closure — the exact bug the backfill exists to fix,
    resurfacing across a restart."""
    root, ext = os.path.splitext(path)
    return root + ".prior" + (ext or ".npz")


_PRIOR_SENTINEL = "prior_sidecar_marker"


def save_prior_sidecar(path: str, prior: Any,
                       config_json: Optional[str] = None) -> str:
    """Write the map prior as `path`'s .prior sidecar; returns the path.
    Same clobber guard as the voxel sidecar."""
    pp = prior_sidecar_path(path)
    if os.path.exists(pp) and not _is_prior_sidecar(pp):
        raise ValueError(
            f"{pp} exists and is not a prior sidecar (a checkpoint named "
            f"with the reserved '.prior' suffix?); refusing to overwrite")
    save_checkpoint(pp, {"prior": prior, _PRIOR_SENTINEL: np.int8(1)},
                    config_json=config_json)
    return pp


def load_prior_sidecar(path: str, template_grid: Any,
                       running_config_json: Optional[str] = None) -> Any:
    """Load `path`'s prior sidecar, or None when no sidecar exists.
    ValueError on non-sidecar collision, shape drift, or config drift —
    one validation path for demo --resume and HTTP /load."""
    pp = prior_sidecar_path(path)
    if not os.path.exists(pp):
        return None
    if not _is_prior_sidecar(pp):
        raise ValueError(
            f"{pp} is not a prior sidecar (name collision with a "
            f"checkpoint named '.prior'?); refusing to load")
    state, cfg_json = load_checkpoint(
        pp, {"prior": template_grid, _PRIOR_SENTINEL: np.int8(0)})
    if cfg_json is not None and running_config_json is not None:
        from jax_mapping.config import configs_equivalent
        if not configs_equivalent(cfg_json, running_config_json):
            raise ValueError(
                "prior sidecar config differs from the running config")
    return state["prior"]


def clear_prior_sidecar(path: str) -> bool:
    """Remove checkpoint `path`'s .prior sidecar if one exists; returns
    whether a file was removed. SENTINEL-CHECKED: a non-sidecar file at
    the sidecar path (a user checkpoint named '.prior' — the collision
    the save/load guards refuse with ValueError) is left alone, because
    a cleanup helper must not bypass the clobber guard."""
    pp = prior_sidecar_path(path)
    if os.path.exists(pp) and _is_prior_sidecar(pp):
        os.unlink(pp)
        return True
    return False


def _is_prior_sidecar(pp: str) -> bool:
    try:
        with np.load(pp) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return _PRIOR_SENTINEL in meta.get("keys", [])
    except Exception:
        return False


def keyframe_sidecar_path(path: str) -> str:
    """Sidecar for the 3D depth-keyframe ring next to a 2D checkpoint.

    Ships separately from the voxel-grid sidecar because its arrays are
    VARIABLE-length (K keyframes) — the template-checked checkpoint
    format pins leaf counts and shapes, and folding the ring into the
    grid sidecar would refuse every pre-round-5 sidecar on load."""
    root, ext = os.path.splitext(path)
    return root + ".voxelkf" + (ext or ".npz")


_KF_KEYS = ("depths", "rels", "node_idx", "thins", "robot")


def save_keyframe_sidecar(path: str, kf: dict,
                          config_json: Optional[str] = None) -> str:
    """Write the keyframe ring (`voxel_mapper.snapshot_keyframes()`
    dict) as `path`'s .voxelkf sidecar; returns the sidecar path."""
    missing = [k for k in _KF_KEYS if k not in kf]
    if missing:
        raise ValueError(f"keyframe snapshot missing keys {missing}")
    kp = keyframe_sidecar_path(path)
    if os.path.exists(kp) and not _is_keyframe_sidecar(kp):
        # Same refuse-to-clobber class as save_voxel_sidecar: a
        # checkpoint NAMED "x.voxelkf" collides with checkpoint "x"'s
        # keyframe sidecar, and silent data loss is worse than an error.
        raise ValueError(
            f"{kp} exists and is not a keyframe sidecar (a checkpoint "
            f"named with the reserved '.voxelkf' suffix?); refusing to "
            f"overwrite")
    meta = {"config": config_json, "version": 1, "kind": "voxel_keyframes"}
    arrays = {k: np.asarray(kf[k]) for k in _KF_KEYS}
    arrays[_META_KEY] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    tmp = kp + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, kp)
    return kp


def load_keyframe_sidecar(path: str,
                          running_config_json: Optional[str] = None):
    """Load `path`'s keyframe ring, or None when no sidecar exists
    (pre-round-5 checkpoints: the ring simply starts empty, exactly the
    pre-persistence behavior). Raises ValueError on a wrong-kind file or
    config drift."""
    kp = keyframe_sidecar_path(path)
    if not os.path.exists(kp):
        return None
    with np.load(kp) as z:
        try:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        except Exception:
            meta = {}
        if meta.get("kind") != "voxel_keyframes":
            raise ValueError(
                f"{kp} is not a voxel keyframe sidecar; refusing to load")
        if running_config_json is not None and \
                meta.get("config") is not None:
            from jax_mapping.config import configs_equivalent
            if not configs_equivalent(meta["config"], running_config_json):
                raise ValueError(
                    "keyframe sidecar config differs from the running "
                    "config")
        absent = [k for k in _KF_KEYS if k not in z.files]
        if absent:
            raise ValueError(
                f"keyframe sidecar {kp} missing arrays {absent}")
        out = {k: z[k] for k in _KF_KEYS}
    lens = {k: len(out[k]) for k in _KF_KEYS}
    if len(set(lens.values())) != 1:
        raise ValueError(
            f"keyframe sidecar arrays disagree on length: {lens}")
    return out


def _is_keyframe_sidecar(kp: str) -> bool:
    try:
        with np.load(kp) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return meta.get("kind") == "voxel_keyframes"
    except Exception:
        return False


def _is_voxel_sidecar(vp: str) -> bool:
    try:
        with np.load(vp) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return _VOXEL_SENTINEL in meta.get("keys", [])
    except Exception:
        return False


def world_sidecar_path(path: str) -> str:
    """Sidecar for the bounded-memory world's window state next to a
    2D checkpoint (world/store.py): window origin, epochs, away-set,
    and — when the store has no disk spill tier — the host-LRU tiles
    embedded. With a spill tier the tiles flush to the spill FILE at
    save time and this sidecar is just the re-anchor manifest; restore
    re-anchors immediately and rehydrates lazily on re-entry."""
    root, ext = os.path.splitext(path)
    return root + ".world" + (ext or ".npz")


#: Sidecar arrays a world payload always carries; host_meta/host_tiles
#: ride along only when the store has no disk tier.
_WORLD_KEYS = ("origin_tile", "epochs", "away")


def save_world_sidecar(path: str, payload: dict,
                       config_json: Optional[str] = None) -> str:
    """Write a WorldStore.checkpoint_payload() as `path`'s .world
    sidecar; returns the sidecar path. Same refuse-to-clobber guard as
    the other sidecars, same per-array CRC discipline as the main
    checkpoint (a rotted sidecar must refuse loudly, not re-anchor the
    window at garbage coordinates)."""
    missing = [k for k in _WORLD_KEYS if k not in payload]
    if missing:
        raise ValueError(f"world payload missing keys {missing}")
    wp = world_sidecar_path(path)
    if os.path.exists(wp) and not _is_world_sidecar(wp):
        raise ValueError(
            f"{wp} exists and is not a world sidecar (a checkpoint named "
            f"with the reserved '.world' suffix?); refusing to overwrite")
    arrays = {k: np.asarray(v) for k, v in payload.items()}
    meta = {"config": config_json, "version": 1, "kind": "world_window",
            "keys": sorted(arrays),
            "crc32": {k: _leaf_crc(v) for k, v in arrays.items()}}
    arrays[_META_KEY] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    tmp = wp + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, wp)
    return wp


def load_world_sidecar(path: str,
                       running_config_json: Optional[str] = None):
    """Load `path`'s world-window sidecar, or None when no sidecar
    exists (pre-windowed checkpoints and windowed=False stacks: the
    window simply starts at its anchor, exactly the boot behavior).
    Raises ValueError on a wrong-kind file, CRC failure, or config
    drift — one validation path for launch restore and HTTP /load."""
    wp = world_sidecar_path(path)
    if not os.path.exists(wp):
        return None
    try:
        with np.load(wp) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
            if meta.get("kind") != "world_window":
                raise ValueError(
                    f"{wp} is not a world sidecar; refusing to load")
            out = {k: z[k] for k in meta["keys"]}
    except (OSError, KeyError, json.JSONDecodeError, zipfile.BadZipFile,
            zlib.error) as e:
        raise CheckpointCorrupt(
            f"world sidecar {wp} is unreadable "
            f"({type(e).__name__}: {e})") from e
    crcs = meta.get("crc32", {})
    bad = [k for k, v in out.items()
           if k in crcs and _leaf_crc(v) != crcs[k]]
    if bad:
        raise CheckpointCorrupt(
            f"world sidecar {wp} failed CRC32 verification on "
            f"arrays {bad} — corrupted on disk")
    missing = [k for k in _WORLD_KEYS if k not in out]
    if missing:
        raise ValueError(f"world sidecar {wp} missing arrays {missing}")
    if running_config_json is not None and \
            meta.get("config") is not None:
        from jax_mapping.config import configs_equivalent
        if not configs_equivalent(meta["config"], running_config_json):
            raise ValueError(
                "world sidecar config differs from the running config")
    return out


def clear_world_sidecar(path: str) -> bool:
    """Remove checkpoint `path`'s .world sidecar if one exists —
    sentinel-checked like clear_prior_sidecar (a save from a
    non-windowed stack must clear a stale window manifest so a later
    windowed resume can't re-anchor at a dead origin)."""
    wp = world_sidecar_path(path)
    if os.path.exists(wp) and _is_world_sidecar(wp):
        os.unlink(wp)
        return True
    return False


def _is_world_sidecar(wp: str) -> bool:
    try:
        with np.load(wp) as z:
            meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        return meta.get("kind") == "world_window"
    except Exception:
        return False


def checkpoint_bytes(state: Any, config_json: Optional[str] = None) -> bytes:
    """In-memory variant (for shipping state over a wire/HTTP)."""
    buf = io.BytesIO()
    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(state)
    arrays = {_path_str(k): np.asarray(v) for k, v in leaves_with_paths}
    meta = {"keys": list(arrays.keys()), "config": config_json, "version": 1}
    arrays[_META_KEY] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()
