"""Checkpoint/resume for device state pytrees.

The reference loses its whole map on restart — slam_toolbox's serialization
API exists but is never invoked (`enable_interactive_mode: true` at
`/root/reference/server/thymio_project/config/slam_config.yaml:32`,
SURVEY.md §5 "Checkpoint / resume: none"). Here any pytree of arrays
(SlamState, FleetState, raw grids) round-trips exactly through one `.npz`
file, with the config JSON embedded so a resume can detect shape drift.

Plain npz rather than orbax: single-host state of a few hundred MB max,
no need for async/multi-host sharded checkpointing machinery — and the file
is inspectable with numpy alone. The layout is flatten-with-paths, so any
NamedTuple nesting (SlamState.graph.poses, ...) keys stably.
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

_META_KEY = "__jax_mapping_meta__"


def _path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(p.name)
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return ".".join(parts) or "value"


def save_checkpoint(path: str, state: Any,
                    config_json: Optional[str] = None) -> None:
    """Write `state` (any pytree of arrays/scalars) to `path` atomically."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    arrays = {}
    keys = []
    for kpath, leaf in leaves_with_paths:
        key = _path_str(kpath)
        assert key not in arrays, f"duplicate checkpoint key {key}"
        arrays[key] = np.asarray(leaf)
        keys.append(key)
    meta = {
        "keys": keys,                       # leaf order for exact rebuild
        "treedef": str(treedef),            # debugging aid only
        "config": config_json,
        "version": 1,
    }
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, path)                   # crash-safe swap


def load_checkpoint(path: str, like: Any
                    ) -> Tuple[Any, Optional[str]]:
    """Read a checkpoint into the structure of `like` (a template pytree,
    e.g. `init_state(cfg)`), returning (state, config_json).

    Leaf dtypes follow the template (so restored state is jit-compatible
    with the running program); a shape mismatch raises with the offending
    key named.
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        data = {k: z[k] for k in meta["keys"]}

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    if len(leaves_with_paths) != len(meta["keys"]):
        raise ValueError(
            f"checkpoint has {len(meta['keys'])} leaves, template has "
            f"{len(leaves_with_paths)} — config/shape drift?")
    new_leaves = []
    for kpath, leaf in leaves_with_paths:
        key = _path_str(kpath)
        if key not in data:
            raise ValueError(f"checkpoint missing leaf {key!r}")
        arr = data[key]
        tmpl = np.asarray(leaf)
        if arr.shape != tmpl.shape:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {arr.shape} != template "
                f"{tmpl.shape} — was the config changed?")
        new_leaves.append(jax.numpy.asarray(arr.astype(tmpl.dtype)))
    return jax.tree_util.tree_unflatten(treedef, new_leaves), meta["config"]


def checkpoint_bytes(state: Any, config_json: Optional[str] = None) -> bytes:
    """In-memory variant (for shipping state over a wire/HTTP)."""
    buf = io.BytesIO()
    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(state)
    arrays = {_path_str(k): np.asarray(v) for k, v in leaves_with_paths}
    meta = {"keys": list(arrays.keys()), "config": config_json, "version": 1}
    arrays[_META_KEY] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()
