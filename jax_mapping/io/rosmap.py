"""ROS map_server map format: `<name>.pgm` + `<name>.yaml`.

The artifact every slam_toolbox operator ends a session with (`ros2 run
nav2_map_server map_saver_cli`): a binary P5 PGM raster plus a YAML
sidecar with resolution/origin/thresholds. The reference never saved a
map at all — restart lost it (SURVEY.md §5 checkpoint: "none in project
code") — and the framework's own npz checkpoints are richer but private.
This module speaks the ecosystem format so maps move BETWEEN stacks:
export for Nav2/map_server/localization consumers, import to seed a grid
from a map produced by any ROS SLAM.

Conventions (map_saver's trinary mode):
  occupied (100) -> 0 (black), free (0) -> 254, unknown (-1) -> 205;
  PGM row 0 is the TOP of the image while grid row 0 is min-y, so rows
  flip on both paths (the same flipud the reference's /map-image does,
  `server/.../main.py:266`).

No pyyaml dependency: the sidecar is a flat key: value document both
ways (map_server itself writes exactly this shape).
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

_OCC_PX = 0          # black
_FREE_PX = 254
_UNKNOWN_PX = 205


def save_map(base_path: str, occupancy: np.ndarray, resolution_m: float,
             origin_m: Tuple[float, float]) -> Tuple[str, str]:
    """Write `<base>.pgm` + `<base>.yaml` from an int8 {-1, 0, 100} grid
    (row 0 = min-y, the nav_msgs/OccupancyGrid layout). Returns the two
    paths written."""
    occ = np.asarray(occupancy)
    if occ.ndim != 2:
        raise ValueError(f"expected (H, W) occupancy, got {occ.shape}")
    px = np.full(occ.shape, _UNKNOWN_PX, np.uint8)
    px[occ == 0] = _FREE_PX
    px[occ == 100] = _OCC_PX
    px = np.flipud(px)                       # grid min-y -> image bottom
    pgm_path = base_path + ".pgm"
    yaml_path = base_path + ".yaml"
    h, w = px.shape
    with open(pgm_path, "wb") as f:
        f.write(f"P5\n{w} {h}\n255\n".encode())
        f.write(np.ascontiguousarray(px).tobytes())
    image_name = os.path.basename(pgm_path)
    with open(yaml_path, "w") as f:
        f.write(
            f"image: {image_name}\n"
            "mode: trinary\n"
            f"resolution: {resolution_m}\n"
            f"origin: [{origin_m[0]}, {origin_m[1]}, 0.0]\n"
            "negate: 0\n"
            "occupied_thresh: 0.65\n"
            # The map_server standard value — NOT a nicer-looking 0.2 or
            # 0.25: unknown pixel 205 has p_occ = 50/255 = 0.19607...,
            # which must land ABOVE free_thresh to stay unknown on
            # re-import (0.196 < 0.19607 by construction).
            "free_thresh: 0.196\n")
    return pgm_path, yaml_path


def _parse_yaml(text: str) -> dict:
    """Flat key: value parser for map_server sidecars (plus the one-line
    [x, y, yaw] origin list). Unknown keys are kept as strings."""
    out: dict = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line or ":" not in line:
            continue
        k, v = line.split(":", 1)
        v = v.strip()
        if v.startswith("[") and v.endswith("]"):
            out[k.strip()] = [float(x) for x in v[1:-1].split(",") if
                              x.strip()]
            continue
        try:
            out[k.strip()] = float(v) if "." in v or "e" in v.lower() \
                else int(v)
        except ValueError:
            out[k.strip()] = v
    return out


def load_map(yaml_path: str) -> Tuple[np.ndarray, float,
                                      Tuple[float, float]]:
    """Read a map_server `<name>.yaml` (+ its PGM) back into an int8
    {-1, 0, 100} occupancy grid (row 0 = min-y), resolution, origin.

    Trinary semantics with the standard thresholds: pixel/255 ABOVE
    occupied_thresh of occupancy probability -> 100, below free_thresh ->
    0, else -1 (map_server's interpretation: occupancy p = (255-px)/255
    when negate=0)."""
    with open(yaml_path) as f:
        meta = _parse_yaml(f.read())
    if "image" not in meta or "resolution" not in meta:
        raise ValueError(
            f"{yaml_path}: missing 'image' or 'resolution' key")
    img_path = os.path.join(os.path.dirname(os.path.abspath(yaml_path)),
                            str(meta["image"]))
    with open(img_path, "rb") as f:
        magic = f.readline().strip()
        if magic != b"P5":
            raise ValueError(f"unsupported PGM magic {magic!r} "
                             "(binary P5 only)")
        def _header_line():
            # PNM allows comment lines anywhere in the header — between
            # the magic and dims AND between dims and maxval.
            tokens = f.readline().split()
            while tokens and tokens[0].startswith(b"#"):
                tokens = f.readline().split()
            return tokens

        dims = _header_line()
        try:
            w, h = int(dims[0]), int(dims[1])
            maxval = int(_header_line()[0])
            px = np.frombuffer(f.read(w * h), np.uint8).reshape(h, w)
        except (IndexError, ValueError) as e:
            # Truncated/malformed header or short pixel payload — the
            # hand-rolled parser must surface one exception type so
            # callers' polite-refusal contracts hold.
            raise ValueError(f"malformed PGM {img_path}: {e}") from e
    if maxval != 255:
        raise ValueError(f"unsupported PGM maxval {maxval}")
    negate = int(meta.get("negate", 0))
    p_occ = (px.astype(np.float32) / 255.0 if negate
             else (255.0 - px.astype(np.float32)) / 255.0)
    occ_t = float(meta.get("occupied_thresh", 0.65))
    free_t = float(meta.get("free_thresh", 0.196))
    occ = np.full(px.shape, -1, np.int8)
    occ[p_occ > occ_t] = 100
    occ[p_occ < free_t] = 0
    occ = np.flipud(occ)                     # image bottom -> grid min-y
    origin = meta.get("origin", [0.0, 0.0, 0.0])
    if not isinstance(origin, list) or len(origin) < 2:
        raise ValueError(f"malformed origin {origin!r} "
                         "(expected [x, y, yaw])")
    if len(origin) > 2 and abs(float(origin[2])) > 1e-9:
        # Legal in ROS, but embedding is axis-aligned (same stance as the
        # same-resolution-only rule): importing a rotated map unrotated
        # would put every wall silently in the wrong place.
        raise ValueError(
            f"map origin yaw {origin[2]} != 0: rotated imports are not "
            "supported; re-save the map axis-aligned")
    return (np.ascontiguousarray(occ), float(meta["resolution"]),
            (float(origin[0]), float(origin[1])))


def embed_in_grid(occupancy: np.ndarray, resolution_m: float,
                  origin_m: Tuple[float, float], grid_cfg) -> np.ndarray:
    """Place an imported occupancy raster into a framework-sized
    (size_cells, size_cells) int8 grid at the cell offset its origin
    implies; cells outside the import stay unknown (-1). Same-resolution
    only — resampling an occupancy trichotomy is a policy decision the
    caller should make explicitly."""
    occ = np.asarray(occupancy, np.int8)
    if abs(resolution_m - grid_cfg.resolution_m) > 1e-9:
        raise ValueError(
            f"imported map resolution {resolution_m} != grid "
            f"{grid_cfg.resolution_m}; resample before embedding")
    n = grid_cfg.size_cells
    out = np.full((n, n), -1, np.int8)
    r0 = int(round((origin_m[1] - grid_cfg.origin_m[1]) / resolution_m))
    c0 = int(round((origin_m[0] - grid_cfg.origin_m[0]) / resolution_m))
    src_r = slice(max(0, -r0), min(occ.shape[0], n - r0))
    src_c = slice(max(0, -c0), min(occ.shape[1], n - c0))
    if src_r.stop <= src_r.start or src_c.stop <= src_c.start:
        return out                           # no overlap
    out[src_r.start + r0:src_r.stop + r0,
        src_c.start + c0:src_c.stop + c0] = occ[src_r, src_c]
    return out


#: Exceptions a malformed/missing map-prior import can raise; entry
#: points catch exactly this for their polite-refusal (rc=2) contract —
#: ONE definition so demo and ros_launch cannot drift.
SEED_ERRORS = (OSError, ValueError, KeyError, TypeError, IndexError)


def seed_mapper(mapper, yaml_path: str, grid_cfg) -> int:
    """Load a map_server artifact and seed `mapper` with it as a
    log-odds prior (the full --map-prior pipeline: load -> same-res
    embed -> prior -> mapper.seed_map_prior). Returns the occupied-cell
    count for operator logging; raises one of SEED_ERRORS on bad
    input."""
    occ, res, origin = load_map(yaml_path)
    occ = embed_in_grid(occ, res, origin, grid_cfg)
    mapper.seed_map_prior(logodds_prior(occ))
    return int((occ == 100).sum())


def logodds_prior(occupancy: np.ndarray, occ_logodds: float = 2.0,
                  free_logodds: float = -2.0) -> np.ndarray:
    """An int8 occupancy grid as a log-odds PRIOR for seeding a mapper:
    confident but not saturated, so live scans can still overturn stale
    walls (the use map_server localization gives an imported map)."""
    occ = np.asarray(occupancy)
    lo = np.zeros(occ.shape, np.float32)
    lo[occ == 100] = occ_logodds
    lo[occ == 0] = free_logodds
    return lo
