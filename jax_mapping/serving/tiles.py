"""Revision-keyed tile store with a quadtree overview pyramid.

One `TileStore` serves one 2D uint8 image surface (the thresholded
occupancy gray of the fleet's shared grid, or the voxel mapper's
height map) as fixed-size PNG tiles at pyramid levels 0..L (level k is
2^k x coarser). The store is PULL-based: `refresh()` snapshots the
provider, hashes every tile in ONE jitted on-device reduction
(`ops/grid.tile_hashes`), and re-encodes only tiles whose 64-bit
content hash changed — the steady-state serving cost is proportional
to what the mapper actually touched, not to the map size.

Delta protocol: every re-encoded tile is stamped with the map revision
it changed at; `tiles_since(r)` returns exactly the tiles stamped
newer than `r`. A client that applies an initial `since=0` snapshot
plus every delta reconstructs the live image bit-for-bit
(tests/test_serving.py proves equality against the mapper's grid).

Consistency: tile bytes, per-tile stamps, and the store revision are
installed atomically under `_lock`, so a reader can never observe a
tile whose bytes are older than its stamp (no stale serve, ever).
`_refresh_lock` single-flights the encode work; readers only ever wait
on the brief install/read critical sections.
"""

from __future__ import annotations

import base64
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from jax_mapping.bridge import png as png_codec
from jax_mapping.config import ServingConfig
from jax_mapping.utils import global_metrics as M


def _downsample_max_u8(img):
    """2x block max for continuous-gray surfaces (voxel height maps:
    taller top surface wins, 0 = unmapped loses)."""
    import jax.numpy as jnp
    arr = jnp.asarray(img)
    n0, n1 = arr.shape
    return arr.reshape(n0 // 2, 2, n1 // 2, 2).max(axis=(1, 3))


class TileStore:
    """Tile cache over one image provider.

    `snapshot_fn() -> (revision, image, dirty_hint)`: `revision` is the
    provider's monotonic content revision; `image` is the full-res 2D
    uint8 array (device or host) in GRID orientation; `dirty_hint` is
    an optional (T, T) bool mask of level-0 tiles the producer marked
    touched since the last snapshot (the mapper's patch-extent marks) —
    a conservative superset used for telemetry (`n_hint_missed` counts
    hash-detected changes the hint failed to cover; it should stay 0).
    The hash diff, not the hint, decides what re-encodes: correctness
    never rides on the producer's bookkeeping.

    `revision_fn()` is the cheap freshness peek (no image work).
    """

    def __init__(self, cfg: ServingConfig, name: str,
                 revision_fn: Callable[[], int],
                 snapshot_fn: Callable[[], Tuple[int, object,
                                                 Optional[np.ndarray]]],
                 downsample_fn: Optional[Callable] = None,
                 meta: Optional[dict] = None,
                 on_install: Optional[Callable[[int], None]] = None):
        self.cfg = cfg
        self.name = name
        self._revision_fn = revision_fn
        self._snapshot_fn = snapshot_fn
        self._downsample_fn = downsample_fn
        #: Telemetry hook called with the committed store revision
        #: after each refresh that re-installed (the pipeline ledger's
        #: tile-re-encoded waypoint). Invoked OUTSIDE both store locks
        #: (lint B2: no foreign code under a lock); failures are
        #: contained — telemetry must never break serving.
        self._on_install = on_install
        self.meta = dict(meta or {})
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        #: (level, ty, tx) -> (revision_changed_at, png_bytes)
        self._tiles: Dict[Tuple[int, int, int], Tuple[int, bytes]] = {}
        #: per-level (T, T, 2) uint32 hash arrays from the last refresh.
        self._hashes: List[Optional[np.ndarray]] = []
        self.revision = -1          # provider revisions start at 0
        self.n_refreshes = 0
        self.n_tiles_encoded = 0
        self.n_tiles_clean_skipped = 0
        self.n_hint_missed = 0
        self._level_sizes: Optional[List[int]] = None
        #: Bounded-memory serving (world/store.py): level-0 tiles the
        #: window evicted carry a typed `evicted` marker in the delta
        #: stream instead of PNG bytes — (ty, tx) -> revision stamped
        #: at eviction. `evicted_epoch` counts eviction-state
        #: transitions (the /tiles ETag `-w` suffix source, so a cache
        #: validator can never 304 across an eviction flip).
        self._evicted_stamps: Dict[Tuple[int, int], int] = {}
        self._evicted_mask: Optional[np.ndarray] = None
        self.evicted_epoch = 0
        self.n_tiles_evicted_served = 0

    # -- geometry ------------------------------------------------------------

    def _levels_for(self, size: int) -> List[int]:
        """Pyramid level edge sizes: full-res first, each next level 2x
        coarser, stopping at the configured depth or when a level would
        shrink below one tile / stop dividing evenly."""
        t = self.cfg.tile_cells
        if size % t:
            raise ValueError(
                f"{self.name}: image edge {size} not divisible by "
                f"ServingConfig.tile_cells={t}")
        sizes = [size]
        while (len(sizes) < self.cfg.pyramid_levels
               and sizes[-1] // 2 >= t and (sizes[-1] // 2) % t == 0):
            sizes.append(sizes[-1] // 2)
        return sizes

    # -- refresh -------------------------------------------------------------

    def refresh(self) -> int:
        """Bring the cache up to the provider's revision; returns the
        store revision afterwards. Cheap when already fresh (one
        revision peek). Single-flighted: concurrent callers serialize
        on `_refresh_lock`, each re-checking freshness on entry."""
        with self._refresh_lock:
            rev = int(self._revision_fn())
            with self._lock:
                if rev == self.revision:
                    return self.revision
            # The serving-snapshot latency stage (obs histograms):
            # covers the mapper snapshot + hash/diff/re-encode — the
            # cost a /tiles poller pays when the map moved. The cheap
            # already-fresh peek above is deliberately outside it.
            with M.stages.stage("serving.snapshot"):
                snap = self._snapshot_fn()
                # Windowed providers return a 4th element: the (T, T)
                # bool mask of level-0 tiles currently evicted from the
                # live window (serving degrades them to typed markers).
                if len(snap) == 4:
                    rev, image, hint, evicted = snap
                else:
                    rev, image, hint = snap
                    evicted = None
                rev = int(rev)
                self._install(rev, image, hint, evicted)
        if self._on_install is not None:
            # After BOTH locks release: the commit is visible, the
            # waypoint stamp is honest, and no foreign code ran under
            # a serving lock.
            try:
                self._on_install(rev)
            except Exception:                     # noqa: BLE001
                pass                              # telemetry only
        return rev

    def _install(self, rev: int, image, hint: Optional[np.ndarray],
                 evicted: Optional[np.ndarray] = None) -> None:
        """Hash, diff, and re-encode under `_refresh_lock`; commit
        atomically under `_lock`. Caller holds `_refresh_lock`."""
        from jax_mapping.ops import grid as G
        import jax.numpy as jnp

        t = self.cfg.tile_cells
        img = jnp.asarray(image)
        if img.shape[0] != img.shape[1]:
            # The pyramid, manifest meta and client mosaics are all
            # square-edged; a rectangular provider must be rejected
            # loudly, not crash inside a reshape.
            raise ValueError(
                f"{self.name}: tile serving needs a square image, got "
                f"{tuple(img.shape)}")
        sizes = self._levels_for(int(img.shape[0]))
        down = self._downsample_fn or G.downsample_gray
        imgs = [img]
        for _ in sizes[1:]:
            imgs.append(down(imgs[-1]))
        hashes = [np.asarray(G.tile_hashes(im, t)) for im in imgs]

        first = not self._hashes
        encoded: Dict[Tuple[int, int, int], Tuple[int, bytes]] = {}
        n_clean = 0
        hint_missed = 0
        for lvl, (im, h) in enumerate(zip(imgs, hashes)):
            if first:
                changed = np.ones(h.shape[:2], bool)
            else:
                changed = np.any(h != self._hashes[lvl], axis=-1)
            if lvl == 0 and hint is not None and not first:
                hint_missed += int(np.count_nonzero(changed & ~hint))
            if lvl == 0 and evicted is not None:
                # Evicted level-0 tiles serve a typed marker, never
                # bytes: skip the encode here; the commit below stamps
                # them. (The mosaic paints them unknown, so the hash
                # still tracks content — re-entry re-encodes normally.)
                changed = changed & ~evicted
            n_clean += int(changed.size - np.count_nonzero(changed))
            if not changed.any():
                continue
            host = np.asarray(im)      # fetch this level once, then slice
            for ty, tx in np.argwhere(changed):
                tile = host[ty * t:(ty + 1) * t, tx * t:(tx + 1) * t]
                encoded[(lvl, int(ty), int(tx))] = (rev, png_codec.encode_gray(
                    tile, compress_level=self.cfg.png_compress_level))

        with self._lock:
            self._tiles.update(encoded)
            if evicted is not None:
                prev = self._evicted_mask
                for ty, tx in np.argwhere(evicted):
                    key = (int(ty), int(tx))
                    if prev is None or not prev[key]:
                        # Newly evicted: drop the cached bytes so a full
                        # resync can never serve a tile the window no
                        # longer backs, stamp the marker at THIS rev.
                        self._tiles.pop((0,) + key, None)
                        self._evicted_stamps[key] = rev
                        self.evicted_epoch += 1
                if prev is not None:
                    for ty, tx in np.argwhere(prev & ~evicted):
                        self._evicted_stamps.pop((int(ty), int(tx)), None)
                        self.evicted_epoch += 1
                self._evicted_mask = evicted.copy()
            self._hashes = hashes
            self._level_sizes = sizes
            self.revision = rev
            self.n_refreshes += 1
            self.n_tiles_encoded += len(encoded)
            self.n_tiles_clean_skipped += n_clean
            self.n_hint_missed += hint_missed

    # -- serving -------------------------------------------------------------

    def tiles_since(self, since: int, level: Optional[int] = None
                    ) -> Tuple[int, List[dict], dict]:
        """(revision, tile entries stamped newer than `since`, manifest
        meta). Entries carry base64 PNG bytes ready for the JSON route.
        `since=0` with fresh stores returns the full snapshot (every
        tile's first stamp is its first refresh's revision >= 0; clients
        start at since=-1 via the client helper to be safe)."""
        with self._lock:
            rev = self.revision
            sizes = list(self._level_sizes or [])
            entries = [
                {"level": lvl, "ty": ty, "tx": tx, "revision": tile_rev,
                 "png": base64.b64encode(data).decode("ascii")}
                for (lvl, ty, tx), (tile_rev, data)
                in sorted(self._tiles.items())
                if tile_rev > since and (level is None or lvl == level)]
            evicted_entries = [
                {"level": 0, "ty": ty, "tx": tx, "revision": tile_rev,
                 "evicted": True}
                for (ty, tx), tile_rev in sorted(self._evicted_stamps.items())
                if tile_rev > since and (level is None or level == 0)]
            self.n_tiles_evicted_served += len(evicted_entries)
            entries.extend(evicted_entries)
            n_evicted = len(self._evicted_stamps)
        meta = dict(self.meta)
        meta.update({
            "map": self.name,
            "tile_cells": self.cfg.tile_cells,
            "levels": [{"level": i, "size_cells": s}
                       for i, s in enumerate(sizes)],
        })
        if n_evicted or self._evicted_mask is not None:
            meta["evicted_tiles"] = n_evicted
        return rev, entries, meta

    def stats(self) -> dict:
        with self._lock:
            return {
                "revision": self.revision,
                "n_refreshes": self.n_refreshes,
                "n_tiles_encoded": self.n_tiles_encoded,
                "n_tiles_clean_skipped": self.n_tiles_clean_skipped,
                "n_hint_missed": self.n_hint_missed,
                "n_tiles_cached": len(self._tiles),
                "n_tiles_evicted": len(self._evicted_stamps),
                "evicted_epoch": self.evicted_epoch,
            }


class MapServing:
    """The bundle the HTTP plane mounts: tile stores + event channel.

    Wired by `MapApiServer` when the attached mapper's config has
    `serving.enabled`; the mapper's tick thread calls
    `on_map_revision(rev)` (registered as a revision listener, invoked
    OUTSIDE the mapper's state lock) and the channel fans it out to
    every `/map-events` client queue."""

    def __init__(self, cfg: ServingConfig, mapper=None, voxel_mapper=None,
                 events=None, pipeline=None):
        from jax_mapping.serving.events import EventChannel
        self.cfg = cfg
        #: Pipeline latency ledger (obs/pipeline.py) or None: the GRID
        #: store's refresh commits stamp the tile-re-encoded waypoint
        #: (the freshness chain is the occupancy surface's; the voxel
        #: height map rides outside it).
        self.pipeline = pipeline
        #: `events` carry-over: a mapper restart rebuilds this bundle
        #: around the new node (http_api.rebind_mapper) but must keep
        #: the live EventChannel — connected /map-events clients ride
        #: across the restart and simply see the resumed revisions.
        self.events = events if events is not None \
            else EventChannel(cfg.event_queue_depth)
        self.mapper = mapper
        self.map_store: Optional[TileStore] = None
        self.voxel_store: Optional[TileStore] = None
        if mapper is not None:
            # LOGICAL geometry for the manifest: in windowed mode the
            # served surface is the full addressable lattice (window
            # content in place, evicted tiles as typed markers), so
            # clients keep one fixed world-anchored mosaic however the
            # window moves. full_cfg == cfg when not windowed.
            g = getattr(mapper, "full_cfg", mapper.cfg).grid
            world = getattr(mapper, "world", None)

            def _map_snapshot():
                from jax_mapping.ops import grid as G
                rev, grid, hint = mapper.serving_snapshot()
                if world is None:
                    return rev, G.to_gray(g, grid), hint
                # Compose outside the mapper's state lock, then verify
                # no shift landed mid-compose: every shift/rehydrate
                # bumps the revision, so rev-stability proves the grid
                # and the window origin/away-set belong together.
                for _ in range(4):
                    mosaic, evicted = world.compose_serving(
                        np.asarray(G.to_gray(g, grid)))
                    if mapper.serving_revision() == rev:
                        break
                    rev, grid, hint2 = mapper.serving_snapshot()
                    if hint2 is not None:
                        hint = hint2 if hint is None else (hint | hint2)
                return rev, mosaic, hint, evicted

            self.map_store = TileStore(
                cfg, "grid", mapper.serving_revision, _map_snapshot,
                meta={"resolution_m": g.resolution_m,
                      "origin_m": list(g.origin_m),
                      "size_cells": g.size_cells,
                      "orientation": "grid-row0-min-y"},
                on_install=(None if pipeline is None
                            else pipeline.encoded))
        if voxel_mapper is not None and \
                self._voxel_servable(cfg, voxel_mapper.cfg.voxel):
            v = voxel_mapper.cfg.voxel

            def _voxel_snapshot():
                rev, img = voxel_mapper.serving_snapshot()
                return rev, img, None

            self.voxel_store = TileStore(
                cfg, "voxel-height", voxel_mapper.serving_revision,
                _voxel_snapshot, downsample_fn=_downsample_max_u8,
                meta={"resolution_m": v.resolution_m,
                      "origin_m": list(v.origin_m[:2]),
                      "size_cells": v.size_x_cells,
                      "orientation": "grid-row0-min-y",
                      "palette": "height-ramp"})

    @staticmethod
    def _voxel_servable(cfg: ServingConfig, voxel) -> bool:
        """Tile geometry fits the voxel height map? The store needs a
        square, tile-divisible image; a stack running a rectangular or
        odd-sized voxel grid keeps working — /voxel-tiles just answers
        404 (no store) instead of 500ing on every request, and the 2D
        map serves normally."""
        return (voxel.size_x_cells == voxel.size_y_cells
                and voxel.size_x_cells % cfg.tile_cells == 0)

    def on_map_revision(self, rev: int) -> None:
        """Mapper revision listener — called on the tick thread, outside
        every mapper lock (the lint B2 contract); fans a small event to
        the bounded per-client queues."""
        self.events.emit({"map": "grid", "revision": int(rev)})

    def epoch(self, source: str) -> int:
        """The serving restart epoch stamped into /tiles responses: the
        grid surface follows the mapper's `restart_epoch` (bumped by
        the supervisor's restarter on the replacement node); surfaces
        without restart machinery stay at 0. Clients treat an epoch
        advance as 'drop cache, resync full' — the legitimate way a
        resumed mapper re-serves an older revision."""
        if source == "grid" and self.mapper is not None:
            return int(getattr(self.mapper, "restart_epoch", 0))
        return 0

    def store(self, source: str) -> Optional[TileStore]:
        return self.map_store if source == "grid" else \
            self.voxel_store if source == "voxel-height" else None

    def stats(self) -> dict:
        out = {
            "events": {
                "n_events": self.events.n_events,
                "n_clients": self.events.n_clients(),
                "n_clients_peak": self.events.n_clients_peak,
                "n_dropped": self.events.n_dropped_total(),
            }
        }
        if self.map_store is not None:
            out["grid"] = self.map_store.stats()
        if self.voxel_store is not None:
            out["voxel"] = self.voxel_store.stats()
        return out
