"""Revision-keyed tile store with a quadtree overview pyramid.

One `TileStore` serves one 2D uint8 image surface (the thresholded
occupancy gray of the fleet's shared grid, or the voxel mapper's
height map) as fixed-size PNG tiles at pyramid levels 0..L (level k is
2^k x coarser). The store is PULL-based: `refresh()` snapshots the
provider, hashes every tile in ONE jitted on-device reduction
(`ops/grid.tile_hashes`), and re-encodes only tiles whose 64-bit
content hash changed — the steady-state serving cost is proportional
to what the mapper actually touched, not to the map size.

Delta protocol: every re-encoded tile is stamped with the map revision
it changed at; `tiles_since(r)` returns exactly the tiles stamped
newer than `r`. A client that applies an initial `since=0` snapshot
plus every delta reconstructs the live image bit-for-bit
(tests/test_serving.py proves equality against the mapper's grid).

Consistency: tile bytes, per-tile stamps, and the store revision are
installed atomically under `_lock`, so a reader can never observe a
tile whose bytes are older than its stamp (no stale serve, ever).
`_refresh_lock` single-flights the encode work; readers only ever wait
on the brief install/read critical sections.
"""

from __future__ import annotations

import base64
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from jax_mapping.bridge import png as png_codec
from jax_mapping.config import ServingConfig
from jax_mapping.utils import global_metrics as M


def _downsample_max_u8(img):
    """2x block max for continuous-gray surfaces (voxel height maps:
    taller top surface wins, 0 = unmapped loses)."""
    import jax.numpy as jnp
    arr = jnp.asarray(img)
    n0, n1 = arr.shape
    return arr.reshape(n0 // 2, 2, n1 // 2, 2).max(axis=(1, 3))


class TileStore:
    """Tile cache over one image provider.

    `snapshot_fn() -> (revision, image, dirty_hint)`: `revision` is the
    provider's monotonic content revision; `image` is the full-res 2D
    uint8 array (device or host) in GRID orientation; `dirty_hint` is
    an optional (T, T) bool mask of level-0 tiles the producer marked
    touched since the last snapshot (the mapper's patch-extent marks) —
    a conservative superset used for telemetry (`n_hint_missed` counts
    hash-detected changes the hint failed to cover; it should stay 0).
    The hash diff, not the hint, decides what re-encodes: correctness
    never rides on the producer's bookkeeping.

    `revision_fn()` is the cheap freshness peek (no image work).
    """

    def __init__(self, cfg: ServingConfig, name: str,
                 revision_fn: Callable[[], int],
                 snapshot_fn: Callable[[], Tuple[int, object,
                                                 Optional[np.ndarray]]],
                 downsample_fn: Optional[Callable] = None,
                 meta: Optional[dict] = None,
                 on_install: Optional[Callable[[int], None]] = None):
        self.cfg = cfg
        self.name = name
        self._revision_fn = revision_fn
        self._snapshot_fn = snapshot_fn
        self._downsample_fn = downsample_fn
        #: Telemetry hook called with the committed store revision
        #: after each refresh that re-installed (the pipeline ledger's
        #: tile-re-encoded waypoint). Invoked OUTSIDE both store locks
        #: (lint B2: no foreign code under a lock); failures are
        #: contained — telemetry must never break serving.
        self._on_install = on_install
        self.meta = dict(meta or {})
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        #: (level, ty, tx) -> (revision_changed_at, png_bytes)
        self._tiles: Dict[Tuple[int, int, int], Tuple[int, bytes]] = {}
        #: per-level (T, T, 2) uint32 hash arrays from the last refresh.
        self._hashes: List[Optional[np.ndarray]] = []
        self.revision = -1          # provider revisions start at 0
        self.n_refreshes = 0
        self.n_tiles_encoded = 0
        self.n_tiles_clean_skipped = 0
        self.n_hint_missed = 0
        self._level_sizes: Optional[List[int]] = None

    # -- geometry ------------------------------------------------------------

    def _levels_for(self, size: int) -> List[int]:
        """Pyramid level edge sizes: full-res first, each next level 2x
        coarser, stopping at the configured depth or when a level would
        shrink below one tile / stop dividing evenly."""
        t = self.cfg.tile_cells
        if size % t:
            raise ValueError(
                f"{self.name}: image edge {size} not divisible by "
                f"ServingConfig.tile_cells={t}")
        sizes = [size]
        while (len(sizes) < self.cfg.pyramid_levels
               and sizes[-1] // 2 >= t and (sizes[-1] // 2) % t == 0):
            sizes.append(sizes[-1] // 2)
        return sizes

    # -- refresh -------------------------------------------------------------

    def refresh(self) -> int:
        """Bring the cache up to the provider's revision; returns the
        store revision afterwards. Cheap when already fresh (one
        revision peek). Single-flighted: concurrent callers serialize
        on `_refresh_lock`, each re-checking freshness on entry."""
        with self._refresh_lock:
            rev = int(self._revision_fn())
            with self._lock:
                if rev == self.revision:
                    return self.revision
            # The serving-snapshot latency stage (obs histograms):
            # covers the mapper snapshot + hash/diff/re-encode — the
            # cost a /tiles poller pays when the map moved. The cheap
            # already-fresh peek above is deliberately outside it.
            with M.stages.stage("serving.snapshot"):
                rev, image, hint = self._snapshot_fn()
                rev = int(rev)
                self._install(rev, image, hint)
        if self._on_install is not None:
            # After BOTH locks release: the commit is visible, the
            # waypoint stamp is honest, and no foreign code ran under
            # a serving lock.
            try:
                self._on_install(rev)
            except Exception:                     # noqa: BLE001
                pass                              # telemetry only
        return rev

    def _install(self, rev: int, image, hint: Optional[np.ndarray]) -> None:
        """Hash, diff, and re-encode under `_refresh_lock`; commit
        atomically under `_lock`. Caller holds `_refresh_lock`."""
        from jax_mapping.ops import grid as G
        import jax.numpy as jnp

        t = self.cfg.tile_cells
        img = jnp.asarray(image)
        if img.shape[0] != img.shape[1]:
            # The pyramid, manifest meta and client mosaics are all
            # square-edged; a rectangular provider must be rejected
            # loudly, not crash inside a reshape.
            raise ValueError(
                f"{self.name}: tile serving needs a square image, got "
                f"{tuple(img.shape)}")
        sizes = self._levels_for(int(img.shape[0]))
        down = self._downsample_fn or G.downsample_gray
        imgs = [img]
        for _ in sizes[1:]:
            imgs.append(down(imgs[-1]))
        hashes = [np.asarray(G.tile_hashes(im, t)) for im in imgs]

        first = not self._hashes
        encoded: Dict[Tuple[int, int, int], Tuple[int, bytes]] = {}
        n_clean = 0
        hint_missed = 0
        for lvl, (im, h) in enumerate(zip(imgs, hashes)):
            if first:
                changed = np.ones(h.shape[:2], bool)
            else:
                changed = np.any(h != self._hashes[lvl], axis=-1)
            if lvl == 0 and hint is not None and not first:
                hint_missed += int(np.count_nonzero(changed & ~hint))
            n_clean += int(changed.size - np.count_nonzero(changed))
            if not changed.any():
                continue
            host = np.asarray(im)      # fetch this level once, then slice
            for ty, tx in np.argwhere(changed):
                tile = host[ty * t:(ty + 1) * t, tx * t:(tx + 1) * t]
                encoded[(lvl, int(ty), int(tx))] = (rev, png_codec.encode_gray(
                    tile, compress_level=self.cfg.png_compress_level))

        with self._lock:
            self._tiles.update(encoded)
            self._hashes = hashes
            self._level_sizes = sizes
            self.revision = rev
            self.n_refreshes += 1
            self.n_tiles_encoded += len(encoded)
            self.n_tiles_clean_skipped += n_clean
            self.n_hint_missed += hint_missed

    # -- serving -------------------------------------------------------------

    def tiles_since(self, since: int, level: Optional[int] = None
                    ) -> Tuple[int, List[dict], dict]:
        """(revision, tile entries stamped newer than `since`, manifest
        meta). Entries carry base64 PNG bytes ready for the JSON route.
        `since=0` with fresh stores returns the full snapshot (every
        tile's first stamp is its first refresh's revision >= 0; clients
        start at since=-1 via the client helper to be safe)."""
        with self._lock:
            rev = self.revision
            sizes = list(self._level_sizes or [])
            entries = [
                {"level": lvl, "ty": ty, "tx": tx, "revision": tile_rev,
                 "png": base64.b64encode(data).decode("ascii")}
                for (lvl, ty, tx), (tile_rev, data)
                in sorted(self._tiles.items())
                if tile_rev > since and (level is None or lvl == level)]
        meta = dict(self.meta)
        meta.update({
            "map": self.name,
            "tile_cells": self.cfg.tile_cells,
            "levels": [{"level": i, "size_cells": s}
                       for i, s in enumerate(sizes)],
        })
        return rev, entries, meta

    def stats(self) -> dict:
        with self._lock:
            return {
                "revision": self.revision,
                "n_refreshes": self.n_refreshes,
                "n_tiles_encoded": self.n_tiles_encoded,
                "n_tiles_clean_skipped": self.n_tiles_clean_skipped,
                "n_hint_missed": self.n_hint_missed,
                "n_tiles_cached": len(self._tiles),
            }


class MapServing:
    """The bundle the HTTP plane mounts: tile stores + event channel.

    Wired by `MapApiServer` when the attached mapper's config has
    `serving.enabled`; the mapper's tick thread calls
    `on_map_revision(rev)` (registered as a revision listener, invoked
    OUTSIDE the mapper's state lock) and the channel fans it out to
    every `/map-events` client queue."""

    def __init__(self, cfg: ServingConfig, mapper=None, voxel_mapper=None,
                 events=None, pipeline=None):
        from jax_mapping.serving.events import EventChannel
        self.cfg = cfg
        #: Pipeline latency ledger (obs/pipeline.py) or None: the GRID
        #: store's refresh commits stamp the tile-re-encoded waypoint
        #: (the freshness chain is the occupancy surface's; the voxel
        #: height map rides outside it).
        self.pipeline = pipeline
        #: `events` carry-over: a mapper restart rebuilds this bundle
        #: around the new node (http_api.rebind_mapper) but must keep
        #: the live EventChannel — connected /map-events clients ride
        #: across the restart and simply see the resumed revisions.
        self.events = events if events is not None \
            else EventChannel(cfg.event_queue_depth)
        self.mapper = mapper
        self.map_store: Optional[TileStore] = None
        self.voxel_store: Optional[TileStore] = None
        if mapper is not None:
            g = mapper.cfg.grid

            def _map_snapshot():
                from jax_mapping.ops import grid as G
                rev, grid, hint = mapper.serving_snapshot()
                return rev, G.to_gray(g, grid), hint

            self.map_store = TileStore(
                cfg, "grid", mapper.serving_revision, _map_snapshot,
                meta={"resolution_m": g.resolution_m,
                      "origin_m": list(g.origin_m),
                      "size_cells": g.size_cells,
                      "orientation": "grid-row0-min-y"},
                on_install=(None if pipeline is None
                            else pipeline.encoded))
        if voxel_mapper is not None and \
                self._voxel_servable(cfg, voxel_mapper.cfg.voxel):
            v = voxel_mapper.cfg.voxel

            def _voxel_snapshot():
                rev, img = voxel_mapper.serving_snapshot()
                return rev, img, None

            self.voxel_store = TileStore(
                cfg, "voxel-height", voxel_mapper.serving_revision,
                _voxel_snapshot, downsample_fn=_downsample_max_u8,
                meta={"resolution_m": v.resolution_m,
                      "origin_m": list(v.origin_m[:2]),
                      "size_cells": v.size_x_cells,
                      "orientation": "grid-row0-min-y",
                      "palette": "height-ramp"})

    @staticmethod
    def _voxel_servable(cfg: ServingConfig, voxel) -> bool:
        """Tile geometry fits the voxel height map? The store needs a
        square, tile-divisible image; a stack running a rectangular or
        odd-sized voxel grid keeps working — /voxel-tiles just answers
        404 (no store) instead of 500ing on every request, and the 2D
        map serves normally."""
        return (voxel.size_x_cells == voxel.size_y_cells
                and voxel.size_x_cells % cfg.tile_cells == 0)

    def on_map_revision(self, rev: int) -> None:
        """Mapper revision listener — called on the tick thread, outside
        every mapper lock (the lint B2 contract); fans a small event to
        the bounded per-client queues."""
        self.events.emit({"map": "grid", "revision": int(rev)})

    def epoch(self, source: str) -> int:
        """The serving restart epoch stamped into /tiles responses: the
        grid surface follows the mapper's `restart_epoch` (bumped by
        the supervisor's restarter on the replacement node); surfaces
        without restart machinery stay at 0. Clients treat an epoch
        advance as 'drop cache, resync full' — the legitimate way a
        resumed mapper re-serves an older revision."""
        if source == "grid" and self.mapper is not None:
            return int(getattr(self.mapper, "restart_epoch", 0))
        return 0

    def store(self, source: str) -> Optional[TileStore]:
        return self.map_store if source == "grid" else \
            self.voxel_store if source == "voxel-height" else None

    def stats(self) -> dict:
        out = {
            "events": {
                "n_events": self.events.n_events,
                "n_clients": self.events.n_clients(),
                "n_clients_peak": self.events.n_clients_peak,
                "n_dropped": self.events.n_dropped_total(),
            }
        }
        if self.map_store is not None:
            out["grid"] = self.map_store.stats()
        if self.voxel_store is not None:
            out["voxel"] = self.voxel_store.stats()
        return out
