"""Serving load generator + benchmark: N concurrent synthetic clients
against a live `launch_sim_stack`.

The question this answers with numbers: what does one polling map
client COST, whole-PNG versus tiled-delta? The baseline mode is the
reference's management plane exactly — `GET /map-image` every poll
period, full body every time (the pre-serving contract: no conditional
GET, the 1 s PNG cache saves encode work but never bytes). The delta
mode is the serving subsystem's protocol — one initial `/tiles`
snapshot, then `?since=<revision>` polls that carry only changed tiles.
An extra SSE listener rides along to exercise the `/map-events` push
channel under the same load.

Reported per mode: bytes/client/sec (steady-state: the delta clients'
initial snapshot is amortized out and reported separately), request
latency p50/p99, and the server-side PNG-cache hit-rate — written as a
`BENCH_*`-style JSON by `python bench.py --suite serving`.

Smoke mode (`tests/test_serving.py::test_loadgen_smoke`) runs the same
harness on the tiny config for a few seconds — tier-1-safe.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import List, Optional

import numpy as np


class ClientStats:
    """One synthetic client's accounting (single-thread writer).

    Request latencies land BOTH in the raw list (exact percentiles at
    bench scale) and in the registry's fixed log-bucket histogram
    machinery (`obs/pipeline.FixedHistogram`, the HIST_EDGES_S grid
    every histogram in the repo shares) — the suite JSON reports the
    bucket counts so two runs compare bucket-for-bucket, the ROADMAP
    serving-scale-out contract ("per-percentile latency histograms")."""

    def __init__(self, mode: str):
        from jax_mapping.obs.pipeline import FixedHistogram
        self.mode = mode
        self.bytes_total = 0
        self.snapshot_bytes = 0
        self.latencies_s: List[float] = []
        self.latency_hist = FixedHistogram()
        #: Client-observed revision ages (ms) from the Server-Timing
        #: freshness headers (delta clients only).
        self.revision_ages_ms: List[float] = []
        self.n_polls = 0
        self.n_tiles = 0
        self.errors: List[str] = []

    def observe_latency(self, dt_s: float) -> None:
        self.latencies_s.append(dt_s)
        self.latency_hist.observe(dt_s)


def _percentile(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs), p))


def _png_poller(base: str, stop: threading.Event, poll_s: float,
                stats: ClientStats) -> None:
    """The reference's polling client: full PNG body every period."""
    while not stop.is_set():
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(base + "/map-image",
                                        timeout=10) as r:
                body = r.read()
            stats.bytes_total += len(body)
            stats.observe_latency(time.monotonic() - t0)
            stats.n_polls += 1
        except Exception as e:     # noqa: BLE001 — survey, don't crash
            stats.errors.append(f"{type(e).__name__}: {e}")
        stop.wait(poll_s)


def _delta_poller(base: str, stop: threading.Event, poll_s: float,
                  stats: ClientStats) -> None:
    """The serving client: snapshot once, then revision deltas."""
    from jax_mapping.serving.client import DeltaMapClient
    client = DeltaMapClient(base)
    while not stop.is_set():
        t0 = time.monotonic()
        try:
            # Full-res consumer: level 0 only (the overview pyramid is
            # for zoomed-out dashboards, which would poll a coarse
            # level INSTEAD — mixed-level polling pays for both).
            body = client.poll(level=0)
            stats.observe_latency(time.monotonic() - t0)
            stats.n_polls += 1
            stats.n_tiles += len(body["tiles"])
        except Exception as e:     # noqa: BLE001
            stats.errors.append(f"{type(e).__name__}: {e}")
        stop.wait(poll_s)
    stats.bytes_total = client.bytes_received
    stats.snapshot_bytes = client.snapshot_bytes
    stats.revision_ages_ms = list(client.revision_ages_ms)


def _sse_listener(base: str, stop: threading.Event,
                  stats: ClientStats) -> None:
    """One push-channel client: reconnecting SSE reads until stopped."""
    while not stop.is_set():
        try:
            req = urllib.request.Request(
                base + "/map-events?timeout_s=2")
            with urllib.request.urlopen(req, timeout=10) as r:
                for line in r:
                    stats.bytes_total += len(line)
                    if line.startswith(b"data:"):
                        stats.n_polls += 1
                    if stop.is_set():
                        break
        except Exception as e:     # noqa: BLE001
            stats.errors.append(f"{type(e).__name__}: {e}")
            stop.wait(0.2)


def serving_bench_config():
    """The benchmark's default stack: a mid-size 512^2 grid (CPU-fast,
    but with enough explored area that a whole-map PNG costs real
    bytes) over the tiny config's scan/matcher shapes, 8x8 serving
    tiles. The tiny 256^2 test config compresses to a few hundred
    bytes of PNG — at that size whole-map polling is artificially
    cheap and the comparison says nothing about the 4096^2 target."""
    import dataclasses
    from jax_mapping.config import GridConfig, ServingConfig, tiny_config

    cfg = tiny_config()
    return dataclasses.replace(
        cfg,
        grid=GridConfig(size_cells=512, patch_cells=128, max_range_m=3.0,
                        align_rows=8, align_cols=8),
        serving=ServingConfig(tile_cells=64, pyramid_levels=3,
                              event_wait_max_s=5.0))


def run_serving_benchmark(cfg=None, *, n_clients: int = 8,
                          duration_s: float = 8.0,
                          poll_period_s: float = 0.1,
                          steps_per_burst: int = 5,
                          publish_every_bursts: int = 3,
                          warmup_steps: int = 150,
                          world_cells: int = 440,
                          n_planks: int = 18,
                          n_robots: int = 2, seed: int = 3,
                          out_path: Optional[str] = None) -> dict:
    """Boot a sim stack, drive it, hammer it with concurrent clients.

    Returns (and optionally writes) the benchmark record. The stack
    steps in bursts on a driver thread — faster than real time, like
    the deterministic tests — publishing `/map` every few bursts so the
    whole-PNG route's stamp advances the way a live deployment's would.
    `warmup_steps` run BEFORE any client connects: the first steps pay
    the jit compiles, which are a boot cost, not a serving cost.
    """
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    if cfg is None:
        cfg = serving_bench_config()
    world = W.plank_course(world_cells, cfg.grid.resolution_m,
                           n_planks=n_planks, seed=seed)
    stack = launch_sim_stack(cfg, world, n_robots=n_robots, http_port=0,
                             realtime=False, seed=seed)
    # Steady-state serving scenario: a MATURE map being incrementally
    # updated, not a blank boot. Seed the known walls as a map prior
    # (the localization-bootstrap path) so the whole-PNG baseline
    # carries the real map's content from the first poll — a Thymio
    # covers ~3 mm per tick, so a blank-boot bench would compare
    # serving costs on a nearly-empty map no deployment would run.
    # Exploration still changes the map every tick (free-space carving
    # around each robot) — exactly the delta traffic under test.
    n = cfg.grid.size_cells
    off = (n - world.shape[0]) // 2
    prior = np.zeros((n, n), np.float32)
    prior[off:off + world.shape[0], off:off + world.shape[1]] = \
        np.where(np.asarray(world) > 0.5, 2.0, 0.0)
    stack.mapper.seed_map_prior(prior)
    base = f"http://127.0.0.1:{stack.api.port}"
    stop = threading.Event()
    steps_run = [0]

    # Warm up OUTSIDE the measured window: compile the step pipeline and
    # the tile store's jits, and give the map real content, before the
    # first client byte (boot cost, not serving cost).
    stack.brain.start_exploring()
    stack.run_steps(warmup_steps)
    stack.mapper.publish_map()
    if stack.api.serving is not None:
        stack.api.serving.map_store.refresh()

    def _drive():
        bursts = 0
        while not stop.is_set():
            stack.run_steps(steps_per_burst)
            steps_run[0] += steps_per_burst
            bursts += 1
            if bursts % publish_every_bursts == 0:
                stack.mapper.publish_map()
            # Pace the sim so client polls interleave with map growth
            # instead of racing a CPU-bound step loop for the GIL.
            stop.wait(0.05)

    driver = threading.Thread(target=_drive, name="loadgen-driver")
    driver.start()

    n_png = max(1, n_clients // 2)
    n_delta = max(1, n_clients - n_png)
    png_stats = [ClientStats("png") for _ in range(n_png)]
    delta_stats = [ClientStats("delta") for _ in range(n_delta)]
    sse_stats = ClientStats("sse")
    threads = [threading.Thread(target=_png_poller,
                                args=(base, stop, poll_period_s, s))
               for s in png_stats]
    threads += [threading.Thread(target=_delta_poller,
                                 args=(base, stop, poll_period_s, s))
                for s in delta_stats]
    threads += [threading.Thread(target=_sse_listener,
                                 args=(base, stop, sse_stats))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=15.0)
    driver.join(timeout=15.0)
    elapsed = time.monotonic() - t0

    api = stack.api
    map_image_requests = api.route_requests.get("/map-image", 0)
    png_hits = api.png_cache_hits.get("map", 0)
    serving_stats = api.serving.stats() if api.serving is not None else {}
    stack.shutdown()

    def _mode_summary(stats_list: List[ClientStats]) -> dict:
        from jax_mapping.obs.pipeline import FixedHistogram
        lats = [x for s in stats_list for x in s.latencies_s]
        ages = [x for s in stats_list for x in s.revision_ages_ms]
        total = sum(s.bytes_total for s in stats_list)
        snap = sum(s.snapshot_bytes for s in stats_list)
        n = len(stats_list)
        # Mode-aggregate fixed log-bucket histogram (per-client hists
        # fold bucketwise — same HIST_EDGES_S grid everywhere).
        agg = FixedHistogram()
        for s in stats_list:
            for k, c in enumerate(s.latency_hist.buckets):
                agg.buckets[k] += c
            agg.total_s += s.latency_hist.total_s
            agg.count += s.latency_hist.count
        out = {
            "n_clients": n,
            "polls": sum(s.n_polls for s in stats_list),
            "bytes_total": total,
            "snapshot_bytes": snap,
            "bytes_per_client_per_sec": round(total / n / elapsed, 1),
            "steady_bytes_per_client_per_sec": round(
                (total - snap) / n / elapsed, 1),
            "latency_p50_ms": (None if not lats else round(
                _percentile(lats, 50) * 1e3, 2)),
            "latency_p90_ms": (None if not lats else round(
                _percentile(lats, 90) * 1e3, 2)),
            "latency_p99_ms": (None if not lats else round(
                _percentile(lats, 99) * 1e3, 2)),
            "latency_histogram": {
                "edges_s": list(agg.summary()["edges_s"]),
                "buckets": agg.summary()["buckets"],
                "count": agg.count,
                "sum_s": round(agg.total_s, 6),
                "hist_p50_ms": agg.percentile_ms(50),
                "hist_p90_ms": agg.percentile_ms(90),
                "hist_p99_ms": agg.percentile_ms(99),
            },
            "errors": sorted({e for s in stats_list for e in s.errors}),
        }
        if ages:
            # Client-observed staleness (Server-Timing freshness
            # headers, delta mode): the number BENCH_SERVING throughput
            # lacked — bytes say what serving costs, this says how
            # fresh the map the client holds actually is.
            out["revision_age_ms"] = {
                "n": len(ages),
                "p50": round(_percentile(ages, 50), 2),
                "p90": round(_percentile(ages, 90), 2),
                "p99": round(_percentile(ages, 99), 2),
                "max": round(max(ages), 2),
            }
        return out

    png = _mode_summary(png_stats)
    delta = _mode_summary(delta_stats)
    steady_delta = delta["steady_bytes_per_client_per_sec"]
    reduction = (None if not steady_delta else round(
        png["bytes_per_client_per_sec"] / steady_delta, 1))
    result = {
        "metric": "map_serving_bytes_per_client",
        "suite": "serving",
        "duration_s": round(elapsed, 2),
        "sim_steps": steps_run[0],
        "poll_period_s": poll_period_s,
        "grid_cells": cfg.grid.size_cells,
        "tile_cells": cfg.serving.tile_cells,
        "whole_png_polling": png,
        "tiled_delta": delta,
        "sse_push": {
            "events_received": sse_stats.n_polls,
            "bytes_total": sse_stats.bytes_total,
            "errors": sorted(set(sse_stats.errors)),
        },
        "bytes_reduction_factor": reduction,
        "png_cache_hit_rate": (None if not map_image_requests else round(
            png_hits / map_image_requests, 3)),
        "serving": serving_stats,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
    return result
