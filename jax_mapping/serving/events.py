"""Fan-out push channel for map-revision events.

The `/map-events` route's backbone: the mapper's tick thread emits one
small event per map-revision advance; every connected client (SSE
stream or long-poll) owns a BOUNDED queue. A slow client's queue drops
its OLDEST event on overflow (drop-to-latest backpressure) — revisions
are cumulative (a client that missed revision N learns everything it
needs from N+1), so dropping old events loses no information, and no
client can ever pin server memory. The same bounded-wait contract as
the HTTP plane's 503-degraded path: every wait here takes a timeout.

Lock discipline (analysis/ B1-B3): `EventChannel._lock` only guards the
subscriber list; delivery happens OUTSIDE it on a snapshot, so emitting
never holds one lock while taking another client's (no cross-client
ordering edges, nothing foreign invoked under a lock).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional


class EventSubscription:
    """One client's bounded event mailbox."""

    def __init__(self, depth: int):
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._depth = max(1, int(depth))
        self._closed = False
        self.n_dropped = 0

    def offer(self, event: Any) -> None:
        """Enqueue; on overflow drop the OLDEST event (drop-to-latest)."""
        with self._lock:
            if self._closed:
                return
            if len(self._queue) >= self._depth:
                self._queue.popleft()
                self.n_dropped += 1
            self._queue.append(event)
            self._not_empty.notify()

    def next(self, timeout_s: float) -> Optional[Any]:
        """Pop the oldest pending event, or None on timeout/close."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._lock:
            while not self._queue:
                if self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._not_empty.wait(timeout=remaining)
            return self._queue.popleft()

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()


class EventChannel:
    """Register/unregister client queues; fan events out to all."""

    def __init__(self, depth: int):
        self.depth = depth
        self._lock = threading.Lock()
        self._subs: List[EventSubscription] = []
        self.n_events = 0
        self.n_clients_peak = 0
        #: Drops inherited from CLOSED subscriptions: the exported
        #: counter must stay monotonic (Prometheus rate() reads any
        #: decrease as a counter reset), so a disconnecting client's
        #: drops fold in here instead of vanishing with its queue.
        self._n_dropped_closed = 0

    def subscribe(self) -> EventSubscription:
        sub = EventSubscription(self.depth)
        with self._lock:
            self._subs.append(sub)
            self.n_clients_peak = max(self.n_clients_peak, len(self._subs))
        return sub

    def unsubscribe(self, sub: EventSubscription) -> None:
        sub.close()
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
                self._n_dropped_closed += sub.n_dropped

    def emit(self, event: Any) -> None:
        """Deliver to every subscriber. The subscriber list is
        snapshotted under the channel lock and delivery happens outside
        it — per-queue locks are leaves, never nested."""
        with self._lock:
            subs = list(self._subs)
            self.n_events += 1
        for sub in subs:
            sub.offer(event)

    def n_clients(self) -> int:
        with self._lock:
            return len(self._subs)

    def n_dropped_total(self) -> int:
        with self._lock:
            subs = list(self._subs)
            closed = self._n_dropped_closed
        return closed + sum(s.n_dropped for s in subs)

    def close_all(self) -> None:
        """Shutdown hook: wake and close every subscriber so bounded
        SSE/long-poll loops exit promptly."""
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            sub.close()
