"""Tiled delta map distribution — the serving subsystem.

The reference's management plane re-encodes and re-ships the ENTIRE
occupancy grid as one PNG to every polling client (`server/.../main.py:
241-279`), bounded only by a 1 s wall-clock cache. At fleet scale and
4096^2 grids that whole-map re-send is the dominant serving cost — yet
the mapper KNOWS which cells changed each fusion (the patch/strip
extents of `ops/grid`), so clients should receive tiles and deltas, not
snapshots (the robocentric/incremental map-maintenance argument of
ROG-Map, PAPERS.md).

Pieces:

* `tiles.TileStore` — revision-keyed tile cache with a quadtree overview
  pyramid; re-encodes ONLY tiles whose on-device content hash
  (`ops/grid.tile_hashes`, one jitted reduction) changed.
* `events.EventChannel` — fan-out push for map-revision events with
  per-client bounded queues and drop-to-latest backpressure.
* `tiles.MapServing` — the bundle the HTTP plane mounts: 2D map store,
  optional voxel height-map store (same TileStore, different provider),
  event channel, serving counters.
* `client.DeltaMapClient` — reference client: applies tile deltas to a
  local mosaic, enforcing revision monotonicity (tests + loadgen).
* `loadgen` — concurrent synthetic clients against a live
  `launch_sim_stack`; the serving benchmark behind
  `python bench.py --suite serving`.

`ServingConfig.enabled=False` (config.py) is exact pre-PR behavior.
"""

from jax_mapping.serving.events import EventChannel, EventSubscription
from jax_mapping.serving.tiles import MapServing, TileStore

__all__ = ["EventChannel", "EventSubscription", "MapServing", "TileStore"]
