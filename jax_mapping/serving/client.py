"""Reference delta-map client: snapshot + tile deltas -> local mosaic.

The consumer half of the tile protocol, used by the serving load
generator and the delta-correctness tests: polls `GET /tiles?since=R`,
applies the returned tiles to per-level host mosaics, and ENFORCES the
protocol's safety properties — the server's revision never goes
backwards, and no returned tile is stamped at or before the client's
`since` (a stale tile or a revision regression raises, which is exactly
what the concurrent hammer test leans on).
"""

from __future__ import annotations

import base64
import json
import re
import urllib.request
from typing import Dict, List, Optional

import numpy as np

from jax_mapping.bridge import png as png_codec

#: `Server-Timing: rev;desc="42", age;dur=12.3` — the revision-age
#: entry's duration, milliseconds (the serving tier's freshness
#: stamp: a SERVER monotonic delta since the served revision's
#: install, so the client measures observed staleness without
#: trusting any cross-host wall clock).
_AGE_RE = re.compile(r"\bage;dur=([0-9.]+)")


def parse_revision_age_ms(server_timing: Optional[str]
                          ) -> Optional[float]:
    """The `age;dur=` milliseconds of a Server-Timing header value,
    or None (absent header, no age entry, malformed)."""
    if not server_timing:
        return None
    m = _AGE_RE.search(server_timing)
    if m is None:
        return None
    try:
        return float(m.group(1))
    except ValueError:
        return None


class RevisionRegression(AssertionError):
    """The server violated revision monotonicity for this client."""


class TenantGone(RuntimeError):
    """A tenant route answered 404: the tenant was evicted (or never
    existed) — mission CHURN, not server breakage. Loadgen and
    operators branch on this instead of a generic HTTPError; the
    server's error body rides along as `.detail`."""

    def __init__(self, route: str, detail: str = ""):
        super().__init__(
            f"tenant route {route} is gone"
            + (f": {detail}" if detail else ""))
        self.route = route
        self.detail = detail


class DeltaMapClient:
    """Polls one tile route and maintains the reconstructed mosaics."""

    def __init__(self, base_url: str, route: str = "/tiles",
                 timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.route = route
        self.timeout_s = timeout_s
        self.revision = -1            # pre-snapshot: everything is new
        self.meta: dict = {}
        #: level -> (size, size) uint8 mosaic (unknown-127 before the
        #: first covering tile arrives; the first poll covers all).
        self.mosaics: Dict[int, np.ndarray] = {}
        #: Server restart epoch (None until the first response): a
        #: supervisor restart-resume legitimately re-serves an OLDER
        #: revision under a bumped epoch — the client drops its cache
        #: and resyncs full instead of raising RevisionRegression.
        self.epoch: Optional[int] = None
        self.n_polls = 0
        self.n_not_modified = 0
        self.n_tiles_applied = 0
        self.n_tiles_pruned = 0       # evicted-marker prunes (windowed)
        self.n_epoch_resyncs = 0
        self.bytes_received = 0
        self.snapshot_bytes = 0       # first (full) poll's body size
        self._etag: Optional[str] = None
        #: Client-observed staleness (the freshness-SLO tier): the
        #: served revision's age per response, from the Server-Timing
        #: header (server monotonic deltas — no clock trust). None
        #: until a header arrives; the bounded history feeds loadgen's
        #: revision-age percentiles.
        self.last_revision_age_ms: Optional[float] = None
        self.revision_ages_ms: List[float] = []
        self._age_history_cap = 4096
        #: The body's status stamp from the last 200 ("warming" /
        #: "quarantined" / None for steady state) — a quarantined
        #: tenant keeps serving its frozen last-good revision, and
        #: this is how a client tells frozen-by-design from stalled.
        self.state: Optional[str] = None

    # -- protocol ------------------------------------------------------------

    def poll(self, level: Optional[int] = None) -> dict:
        """One delta round trip; returns the decoded response body.

        Replays the server's ETag as `If-None-Match`: a client that is
        already at the live revision pays a body-less 304, not even the
        empty-manifest JSON."""
        # Routes may carry their own query (the per-tenant namespace:
        # route="/tiles?tenant=m0"); extend it instead of double-"?".
        sep = "&" if "?" in self.route else "?"
        url = f"{self.base_url}{self.route}{sep}since={self.revision}"
        if level is not None:
            url += f"&level={level}"
        req = urllib.request.Request(url)
        if self._etag:
            req.add_header("If-None-Match", self._etag)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                raw = r.read()
                self._etag = r.headers.get("ETag") or self._etag
                self._note_age(r.headers.get("Server-Timing"))
        except urllib.error.HTTPError as e:
            if e.code == 404 and "tenant=" in self.route:
                # Tenant churn, typed: an evicted/unknown tenant's 404
                # must read as TenantGone, not generic breakage.
                try:
                    detail = json.loads(e.read() or b"{}").get(
                        "error", "")
                except (ValueError, OSError):
                    detail = ""
                raise TenantGone(self.route, detail) from e
            if e.code != 304:
                raise
            e.read()
            # A 304 confirms freshness like a body does — the age
            # header rides it, and the client's staleness series must
            # include its already-current polls.
            self._note_age(e.headers.get("Server-Timing"))
            self.n_polls += 1
            self.n_not_modified += 1
            return {"revision": self.revision, "since": self.revision,
                    "tiles": [], "not_modified": True}
        body = json.loads(raw)
        first = self.n_polls == 0
        self.n_polls += 1
        self.bytes_received += len(raw)
        if first:
            self.snapshot_bytes = len(raw)
        self.state = body.get("state")
        if self._note_epoch(body):
            # Restart epoch advanced: this body is a delta against a
            # serving generation we no longer share. Cache dropped;
            # refetch the full snapshot under the new epoch (the reset
            # put since back to -1, so this recursion terminates).
            return self.poll(level)
        self.apply(body)
        return body

    def _note_age(self, server_timing: Optional[str]) -> None:
        age = parse_revision_age_ms(server_timing)
        if age is None:
            return
        self.last_revision_age_ms = age
        self.revision_ages_ms.append(age)
        del self.revision_ages_ms[:-self._age_history_cap]

    def _note_epoch(self, body: dict) -> bool:
        """Track the server's restart epoch; on an advance, drop every
        cached artifact (mosaics, revision, ETag) and report True —
        the caller must resync full. The mapper restart-resume case:
        revision regression under a NEW epoch is protocol-legal."""
        ep = int(body.get("epoch", 0))
        if self.epoch is None:
            self.epoch = ep
            return False
        if ep == self.epoch:
            return False
        self.epoch = ep
        self.revision = -1
        self.mosaics = {}
        self.meta = {}
        self._etag = None
        self.n_epoch_resyncs += 1
        return True

    def apply(self, body: dict) -> None:
        """Apply one /tiles response; raises on any staleness. Epoch
        handling lives in poll(); direct apply() callers are expected
        to feed one epoch's bodies."""
        rev = int(body["revision"])
        if rev < self.revision:
            raise RevisionRegression(
                f"server revision went backwards: {self.revision} -> {rev}")
        since = int(body.get("since", self.revision))
        self.meta = {k: v for k, v in body.items() if k != "tiles"}
        t = int(body["tile_cells"])
        sizes = {lv["level"]: lv["size_cells"] for lv in body["levels"]}
        for tile in body["tiles"]:
            tile_rev = int(tile["revision"])
            if tile_rev <= since:
                raise RevisionRegression(
                    f"tile {tile['level']}/{tile['ty']}/{tile['tx']} "
                    f"stamped {tile_rev} <= since={since}: stale serve")
            lvl = int(tile["level"])
            if lvl not in self.mosaics:
                self.mosaics[lvl] = np.full(
                    (sizes[lvl], sizes[lvl]), 127, np.uint8)
            ty, tx = int(tile["ty"]), int(tile["tx"])
            if tile.get("evicted"):
                # Typed tile-evicted marker (the bounded-memory world):
                # the window no longer backs this tile — prune it to
                # unknown instead of treating the byteless entry as a
                # protocol violation. Re-entry re-serves real bytes.
                self.mosaics[lvl][ty * t:(ty + 1) * t,
                                  tx * t:(tx + 1) * t] = 127
                self.n_tiles_pruned += 1
                continue
            arr = png_codec.decode_gray(
                base64.b64decode(tile["png"]))
            self.mosaics[lvl][ty * t:(ty + 1) * t,
                              tx * t:(tx + 1) * t] = arr
            self.n_tiles_applied += 1
        self.revision = rev

    def image(self, level: int = 0) -> np.ndarray:
        """The reconstructed mosaic at a pyramid level (grid
        orientation; `np.flipud` for display coordinates)."""
        return self.mosaics[level]
