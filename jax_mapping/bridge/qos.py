"""QoS profiles with DDS semantics.

The reference's transport fidelity matters: Best-Effort reliability on
`/scan` was *required* for fluid map updates over Wi-Fi (report.pdf §V.A,
SURVEY.md §5 "Distributed communication backend"), so the in-process bus
reproduces the observable difference — Best-Effort subscriptions drop the
oldest sample when their queue is full and may drop/reorder under injected
loss, Reliable subscriptions never lose a sample (publisher blocks on a full
queue instead).
"""

from __future__ import annotations

import dataclasses
import enum


class Reliability(enum.Enum):
    BEST_EFFORT = "best_effort"
    RELIABLE = "reliable"


class Durability(enum.Enum):
    VOLATILE = "volatile"
    # Late-joining subscribers receive the last published sample — what RViz
    # relies on for `/map` (map_qos transient local in ROS).
    TRANSIENT_LOCAL = "transient_local"


@dataclasses.dataclass(frozen=True)
class QoSProfile:
    depth: int = 10
    reliability: Reliability = Reliability.RELIABLE
    durability: Durability = Durability.VOLATILE


#: `/scan` over lossy links (report.pdf §V.A).
qos_sensor_data = QoSProfile(depth=5, reliability=Reliability.BEST_EFFORT)

#: `/map` to late-joining viewers.
qos_map = QoSProfile(depth=1, reliability=Reliability.RELIABLE,
                     durability=Durability.TRANSIENT_LOCAL)

#: default pub/sub profile.
qos_default = QoSProfile()
