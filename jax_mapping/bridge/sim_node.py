"""Simulation node: the LD06 driver + physical world, in one box.

Plays the role of the Pi half of the reference (SURVEY.md §3.3): a fixed-rate
loop that produces `sensor_msgs/LaserScan` on `{ns}scan` — plus the physics
the workshop floor provided for free. Each tick it:

  1. reads motor targets from the driver (what the brain wrote),
  2. advances the simulated fleet (first-order motor lag + RK2 kinematics,
     `sim.thymio`),
  3. feeds measured wheel speeds + IR prox back into the driver (uint16
     wire encoding included),
  4. raycasts LD06 scans from ground-truth poses (`sim.lidar`) and
     publishes them Best-Effort (report.pdf §V.A).

The scan publish rate defaults to the LD06's ~10 rotations/sec
(`BASELINE.md` "Effective scan ingest").
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from jax_mapping.bridge.brain import robot_ns
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import SimulatedThymioDriver
from jax_mapping.bridge.messages import Header, LaserScan
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.qos import qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig


class SimNode(Node):
    """Ground-truth world + sensor emulation behind the driver surface."""

    def __init__(self, cfg: SlamConfig, bus: Bus,
                 driver: SimulatedThymioDriver, world: np.ndarray,
                 world_res_m: float, tf: Optional[TfTree] = None,
                 rate_hz: float = 10.0, seed: int = 0,
                 realtime: bool = True, depth_cam: bool = False,
                 wall_height_m: float = 0.5):
        super().__init__("sim_node", bus, tf)
        import jax
        import jax.numpy as jnp

        from jax_mapping.sim import lidar, thymio

        self.cfg = cfg
        self.driver = driver
        self._lidar, self._thymio, self._jnp = lidar, thymio, jnp
        self.world = jnp.asarray(np.asarray(world, bool))
        self.world_res_m = world_res_m
        self.rate_hz = rate_hz
        self.n_samples = int(cfg.scan.range_max_m / (world_res_m * 0.5))
        R = driver.n_robots
        self.sim_state = thymio.init_fleet(cfg.robot, jax.random.PRNGKey(seed),
                                           R)
        self.scan_pubs = [
            self.create_publisher(f"{robot_ns(i, R)}scan", qos_sensor_data)
            for i in range(R)]
        # Optional simulated depth camera (BASELINE configs[4]): renders a
        # per-robot depth image each tick for the 3D voxel pipeline.
        self.depth_cam = depth_cam
        self.wall_height_m = wall_height_m
        if depth_cam:
            from jax_mapping.sim import depthcam
            self._depthcam = depthcam
            self.depth_n_samples = max(
                16, int(cfg.depthcam.range_max_m / (world_res_m * 0.5)))
            self.depth_pubs = [
                self.create_publisher(f"{robot_ns(i, R)}depth",
                                      qos_sensor_data)
                for i in range(R)]
        # Adversarial sensor-fault state (resilience/faultplan.py kinds
        # wheel_slip / lidar_miscal / ghost_returns / scan_jam). Written
        # only by FaultPlan.apply on the run_steps thread, read by
        # step() — the deterministic step clock serializes them; the
        # identity values keep the healthy hot path byte-identical
        # (_faults_active gates every application).
        self._wheel_slip = np.ones(R, np.float32)
        self._lidar_miscal = np.zeros(R, np.float32)
        self._ghost_frac = np.zeros(R, np.float32)
        self._scan_jam = np.zeros(R, bool)
        #: Last healthy ranges per robot — what a jammed sensor keeps
        #: re-reporting (frozen data, fresh stamps).
        self._jam_cache = [None] * R
        self._fault_seed = seed
        #: Scripted world dynamics (scenarios/dynamics.py), or None —
        #: the static-world stack exactly. Written once at launch by
        #: attach_world_dynamics, mutated through the set_door/set_crowd
        #: boundary (FaultPlan world kinds), consumed at the top of
        #: step() (re-upload only when geometry changed).
        self._world_dyn = None
        self.n_world_updates = 0
        self.n_steps = 0
        if realtime:
            self.create_timer(1.0 / rate_hz, self.step)

    # -- scripted world dynamics (scenarios/; FaultPlan world kinds) ---------

    def attach_world_dynamics(self, dyn) -> None:
        """Arm a WorldDynamics: its base world must be THIS sim's world
        (same shape; the scenario engine owns composition from here
        on). step() re-uploads the composed bitmap when it changes."""
        if dyn.base.shape != tuple(self.world.shape):
            raise ValueError(
                f"world dynamics base {dyn.base.shape} != sim world "
                f"{tuple(self.world.shape)}")
        self._world_dyn = dyn

    def set_door(self, name: str, closed: bool) -> None:
        """Close (or re-open) a registered door — the `door_close`
        scenario kind's boundary."""
        if self._world_dyn is None:
            raise RuntimeError(
                "no world dynamics attached (launch the stack with a "
                "scenarios.WorldDynamics to script doors)")
        self._world_dyn.set_door(name, closed)

    def set_crowd(self, cid: int, radius_m) -> None:
        """Activate/clear a moving crowd blob — the `crowd` scenario
        kind's boundary (None radius = gone)."""
        if self._world_dyn is None:
            raise RuntimeError(
                "no world dynamics attached (launch the stack with a "
                "scenarios.WorldDynamics to script crowds)")
        self._world_dyn.set_crowd(cid, radius_m)

    # -- adversarial sensor-fault boundary (FaultPlan setters) ---------------

    def set_wheel_slip(self, robot: int, factor: float) -> None:
        """Bias robot's MEASURED wheel speeds by `factor` (1.0 = healthy):
        odometry integrates motion the robot did not make."""
        self._wheel_slip[robot] = factor

    def set_lidar_miscal(self, robot: int, offset_rad: float) -> None:
        """Rotate robot's lidar mount by `offset_rad` (0 = healthy):
        every beam reports the range of a rotated world angle."""
        self._lidar_miscal[robot] = offset_rad

    def set_ghost_returns(self, robot: int, frac: float) -> None:
        """Replace a seeded `frac` of robot's live beams with spurious
        short ranges (0 = healthy)."""
        self._ghost_frac[robot] = frac

    def set_scan_jam(self, robot: int, jammed: bool) -> None:
        """Freeze robot's scan at the last healthy reading (fresh
        stamps, stale data) until cleared. Clearing drops the cache so
        a LATER jam window pins its own onset reading — not a scan
        recorded during a previous fault epoch (the cache only
        refreshes while some fault is active)."""
        self._scan_jam[robot] = jammed
        if not jammed:
            self._jam_cache[robot] = None

    def _faults_active(self) -> bool:
        return bool((self._wheel_slip != 1.0).any()
                    or (self._lidar_miscal != 0.0).any()
                    or (self._ghost_frac != 0.0).any()
                    or self._scan_jam.any())

    def truth_poses(self) -> np.ndarray:
        return np.asarray(self.sim_state.poses)

    def step(self) -> None:
        """One physics+sensor tick (call directly for faster-than-realtime
        runs; the timer drives it in realtime mode)."""
        cfg = self.cfg
        if self._world_dyn is not None:
            # Scripted world mutations land BEFORE this step's physics
            # and raycast (FaultPlan fires on the same step clock), so a
            # door closed at step k is solid in step k's scans.
            new_world = self._world_dyn.world_if_changed(self.n_steps)
            if new_world is not None:
                self.world = self._jnp.asarray(new_world)
                self.n_world_updates += 1
        targets = self._jnp.asarray(self.driver.targets().astype(np.float32))
        self.sim_state, measured = self._thymio.step_fleet(
            cfg.robot, self.sim_state, targets, 1.0 / self.rate_hz,
            cfg.robot.speed_noise_frac)
        prox = self._lidar.ir_proximity(self.world, self.world_res_m,
                                        self.sim_state.poses)
        prox7 = np.zeros((self.driver.n_robots, 7), np.int32)
        prox7[:, :5] = np.clip(np.asarray(prox), 0, 4500).astype(np.int32)
        faults = self._faults_active()
        measured_np = np.asarray(measured)
        if faults:
            # wheel_slip: odometry bias at the measured-speed boundary
            # (ground truth untouched — sim/thymio.apply_wheel_slip).
            measured_np = self._thymio.apply_wheel_slip(
                measured_np, self._wheel_slip)
        self.driver.ingest_state(measured_np, prox7)

        scan_poses = self.sim_state.poses
        if faults:
            # lidar_miscal: raycast from heading-offset poses — beam k
            # reports a rotated world angle but keeps its label.
            scan_poses = self._jnp.asarray(self._lidar.apply_lidar_miscal(
                np.asarray(scan_poses), self._lidar_miscal))
        scans = self._lidar.simulate_scans(
            cfg.scan, self.world, self.world_res_m, self.n_samples,
            scan_poses)
        scans_np = np.asarray(scans)
        if faults:
            scans_np = scans_np.copy()   # device fetch may be read-only
            for i in range(self.driver.n_robots):
                if self._scan_jam[i]:
                    # Frozen data, fresh stamps; the cache pins the
                    # reading at jam onset.
                    if self._jam_cache[i] is None:
                        self._jam_cache[i] = scans_np[i].copy()
                    else:
                        scans_np[i] = self._jam_cache[i]
                else:
                    self._jam_cache[i] = scans_np[i].copy()
                if self._ghost_frac[i] > 0.0:
                    # Seeded per (launch seed, step, robot): two
                    # same-seed runs ghost the identical beams.
                    rng = np.random.default_rng(
                        (self._fault_seed, self.n_steps, i))
                    scans_np[i] = self._lidar.apply_ghost_returns(
                        cfg.scan, scans_np[i], float(self._ghost_frac[i]),
                        rng)
        stamp = time.monotonic()
        for i, pub in enumerate(self.scan_pubs):
            pub.publish(LaserScan(
                header=Header(stamp=stamp,
                              frame_id=f"{robot_ns(i, len(self.scan_pubs))}"
                                       f"base_laser"),
                angle_min=cfg.scan.angle_min_rad,
                angle_increment=cfg.scan.angle_increment_rad,
                scan_time=1.0 / self.rate_hz,
                range_min=cfg.scan.range_min_m,
                range_max=cfg.scan.range_max_m,
                ranges=scans_np[i, :cfg.scan.n_beams].copy()))

        if self.depth_cam:
            from jax_mapping.bridge.messages import DepthImage
            depths = self._depthcam.render_depths(
                cfg.depthcam, self.world, self.world_res_m,
                self.depth_n_samples, self.sim_state.poses,
                self.wall_height_m)
            depths_np = np.asarray(depths)
            for i, pub in enumerate(self.depth_pubs):
                pub.publish(DepthImage(
                    header=Header(stamp=stamp,
                                  frame_id=f"{robot_ns(i, len(self.depth_pubs))}"
                                           f"base_camera"),
                    depth=depths_np[i]))
        self.n_steps += 1
