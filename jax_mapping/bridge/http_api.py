"""HTTP control/observability API (stdlib http.server; no Flask dependency).

Re-creates the reference's management plane (SURVEY.md §1 L4) with both
variants' routes merged:

* `GET|POST /start`, `GET|POST /stop` — flip `is_exploring`
  (`/root/reference/server/thymio_project/thymio_project/main.py:227-239`);
  stop also forces motors off (pi variant, `pi/src/.../main.py:320-326`).
  GET stays accepted here — deliberately, unlike /save /load below — for
  parity with the reference's documented `curl :5000/start` workflow
  (Flask GET routes): these flip a recoverable flag, while /load
  irreversibly replaces map state.
* `GET /status` — JSON connection/exploring/pose (`pi/src/.../main.py:332-341`).
* `GET /map-image` — latest `/map` as a grayscale PNG, 127 unknown / 255
  free / 0 occupied, flipped to image coords (`server/.../main.py:241-279`).
  The reference declared a 1 s PNG cache but never wrote it (`last_png`
  dead code, `:56-57` vs `:248-249` — SURVEY.md Appendix B); here the cache
  actually works.
* `GET /frontiers` — JSON frontier targets + assignment (new capability).
* `GET /voxel-image` — grayscale height-map PNG of the 3D voxel map
  (BASELINE configs[4]; 404 unless the stack runs with depth_cam).
* `GET /metrics` — framework counters in Prometheus text format, now
  including per-route request counters and a request-latency histogram
  (`jax_mapping_http_request_seconds`).
* `GET /tiles?since=<revision>[&level=k]` — the serving subsystem's
  delta protocol (serving/tiles.py): only the tiles whose content
  changed since the client's revision, as base64 PNGs in a JSON
  manifest, with a quadtree overview pyramid. `GET /voxel-tiles` is the
  height-map twin. 404 when `ServingConfig.enabled` is False.
* `GET /map-events` — SSE push stream of map-revision events
  (`?mode=poll&since=R` long-polls one JSON event instead); per-client
  bounded queues with drop-to-latest backpressure, every wait capped by
  `ServingConfig.event_wait_max_s` (the bounded-wait contract of the
  503-degraded path, applied to push).
* Every map route answers conditional GETs: `ETag` keyed on map stamp /
  voxel fusion key / tile revision, `If-None-Match` hit -> 304 with an
  empty body (pollers stop paying full-PNG bodies for unchanged maps).
* `POST /save[?name=x]`, `POST /load[?name=x]` — checkpoint / restore the
  live SLAM state (grid, poses, graphs, scan rings) through
  `io.checkpoint`. The capability slam_toolbox exposes as its
  serialization service (`enable_interactive_mode`, slam_config.yaml:32)
  but the reference never invokes — here a restart resumes the map
  instead of losing it. Names are basenames inside `checkpoint_dir`
  (no path traversal); load refuses config-drifted checkpoints. POST
  only (ADVICE r3): GET /load would let a link prefetcher or stray
  browser request silently replace the running map; GET answers 405.
* `POST /save-map[?name=x]` — export the live map in the ROS map_server
  format (map.pgm + map.yaml, the map_saver_cli artifact) for external
  consumers; `demo --map-prior` re-imports it (io/rosmap.py).
* `POST /goal?x=..&y=..[&robot=N]` — navigation goal dispatch without
  RViz: the HTTP twin of the SetGoal tool, published through the same
  bus topics the adapter uses (one goal ingress). 400 on malformed,
  out-of-range, non-finite, or out-of-map input.
* `POST /goal/cancel[?robot=N]` — clear a manual goal (the escape hatch
  RViz lacks); the robot reverts to frontier exploration.

Served threaded like the reference (Flask's threaded dev server); shutdown
uses the pi variant's graceful `make_server`/`shutdown` pattern
(`pi/src/.../main.py:364-380`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from jax_mapping.bridge import png as png_codec
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import FrontierArray, OccupancyGrid
from jax_mapping.bridge.qos import qos_map
from jax_mapping.resilience.health import LockTimeout


class MapApiServer:
    """Bind handlers to framework state and serve.

    `brain` needs `start_exploring()`, `stop_exploring()`, `status()` (the
    ThymioBrain surface); map and frontier payloads arrive over the bus.
    """

    def __init__(self, bus: Bus, brain=None, host: str = "127.0.0.1",
                 port: int = 5000, png_cache_s: float = 1.0,
                 extra_status: Optional[Callable[[], dict]] = None,
                 mapper=None, checkpoint_dir: str = "checkpoints",
                 voxel_mapper=None, planner=None, health=None,
                 supervisor=None, recovery=None, devprof=None,
                 lock_timeout_s: Optional[float] = 2.0,
                 socket_timeout_s: Optional[float] = 30.0,
                 pipeline=None, slo=None):
        self.bus = bus
        self.brain = brain
        self.mapper = mapper
        self.voxel_mapper = voxel_mapper
        self.planner = planner
        self.checkpoint_dir = checkpoint_dir
        self.png_cache_s = png_cache_s
        self.extra_status = extra_status
        #: Degraded-mode plumbing (resilience/): FleetHealth and the
        #: Supervisor ride along on /status and /metrics; lock_timeout_s
        #: bounds every node-lock wait a handler makes (expiry -> 503
        #: {"state": "degraded"} instead of a hung worker thread).
        self.health = health
        self.supervisor = supervisor
        #: Estimator guardrails (recovery/manager.py): watchdog states,
        #: quarantine/relocalization counters, anti-stuck ladder and
        #: frontier blacklist ride along on /status and /metrics.
        self.recovery = recovery
        #: Device-side dispatch profiler (obs/devprof.py): per-function
        #: dispatch accounting, recompile counters, memory watermarks
        #: and the collected-so-far cost ledger ride along on /status
        #: (`perf`) and /metrics (`jax_mapping_device_*`). The ledger
        #: exports what collect() already gathered — an HTTP handler
        #: never AOT-compiles.
        self.devprof = devprof
        self.cost_ledger = None
        if devprof is not None:
            from jax_mapping.obs.ledger import CostLedger
            self.cost_ledger = CostLedger(devprof)
        #: Pipeline latency ledger (obs/pipeline.py) or None: serving
        #: routes stamp first-client-delivery waypoints and answer
        #: with `Server-Timing`-style revision-age headers — SERVER
        #: monotonic deltas, so a client measures observed staleness
        #: without trusting any cross-host wall clock.
        self.pipeline = pipeline
        #: Freshness SLO engine (obs/slo.py) or None: `/status.slo` +
        #: `jax_mapping_slo_*` metric families ride along.
        self.slo = slo
        self.lock_timeout_s = lock_timeout_s
        #: Staged warm-up window (ISSUE 12): while a supervisor restart
        #: restores+pre-warms the mapper, serving keeps answering from
        #: the OLD node's last epoch and /status + /tiles stamp
        #: `state=warming` — availability over freshness, made visible.
        #: Set-once-per-window by the restarting thread
        #: (launch.restart_mapper), read bare by handler threads (the
        #: lock-free flag convention: a boolean read can only be one
        #: window edge stale).
        self.warming = False
        #: /status `cold_start` provider wired by launch when the
        #: warm-restart tier is armed (cache counters, warm-pool stats,
        #: warm-up report).
        self.coldstart_status: Optional[Callable[[], dict]] = None
        #: Mission multi-tenancy control plane
        #: (tenancy/controlplane.TenantControlPlane) wired by launch
        #: when TenancyConfig.enabled: `/status.tenancy`,
        #: `jax_mapping_tenant_*` metrics, and per-tenant
        #: `/tiles?tenant=` delta sessions. Set-once before serving,
        #: read bare by handler threads (the lock-free flag
        #: convention).
        self.tenancy = None
        self.n_degraded_responses = 0
        self._lock = threading.Lock()
        #: Request statistics lock: ThreadingHTTPServer runs one worker
        #: thread per connection, and `n_requests += 1` is a read-
        #: modify-write — handler threads racing on it under-count.
        #: Every request counter (totals, per-route, degraded, 304s,
        #: the latency histogram) mutates under THIS dedicated lock so
        #: stats can never contend with the map/frontier state lock.
        self._stats_lock = threading.Lock()
        self._latest_map: Optional[OccupancyGrid] = None
        self._latest_frontiers: Optional[FrontierArray] = None
        # The 1 s PNG cache, implemented for real this time — one policy
        # for every PNG route (see _cached_png).
        self._png_cache: Dict[str, tuple] = {}
        self.png_cache_hits: Dict[str, int] = {}
        self.n_requests = 0
        self.n_png_cache_hits = 0
        self.n_304_responses = 0
        #: Per-route request counters + request-latency histogram
        #: (Prometheus `jax_mapping_http_request_seconds` buckets) —
        #: without these, a serving regression on one route hides
        #: inside the process-wide total.
        self.route_requests: Dict[str, int] = {}
        self._lat_buckets_s = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                               0.5, 1.0, 2.5, 5.0)
        self._lat_counts = [0] * (len(self._lat_buckets_s) + 1)
        self._lat_sum_s = 0.0
        self._lat_n = 0
        #: Tiled delta serving (serving/): built when the attached
        #: mapper's config enables it. ServingConfig.enabled=False (or
        #: no mapper) leaves this None — /tiles, /voxel-tiles and
        #: /map-events answer 404, exact pre-serving behavior.
        self.serving = None
        self._shutting_down = threading.Event()
        if mapper is not None and \
                getattr(mapper.cfg, "serving", None) is not None and \
                mapper.cfg.serving.enabled:
            from jax_mapping.serving import MapServing
            self.serving = MapServing(mapper.cfg.serving, mapper=mapper,
                                      voxel_mapper=voxel_mapper,
                                      pipeline=pipeline)
            mapper.add_revision_listener(self.serving.on_map_revision)

        #: The /metrics exposition, declared once (obs/registry.py):
        #: collectors close over this server and read live state at
        #: render time; registration order is the historical document
        #: order (byte-compatible refactor of the hand-built assembly).
        self._registry = self._build_metrics_registry()

        bus.subscribe("/map", qos_map, callback=self._map_cb)
        bus.subscribe("/frontiers", callback=self._frontiers_cb)

        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):    # silence per-request spam
                pass

            # Per-connection socket timeout (StreamRequestHandler
            # honors the class attribute): a stalled client cannot pin
            # a worker thread forever.
            timeout = socket_timeout_s

            def _dispatch(self, method):
                t0 = time.monotonic()
                extra = {}
                try:
                    res = api.handle(self.path, method=method,
                                     headers=self.headers)
                    status, ctype, body = res[0], res[1], res[2]
                    if len(res) > 3 and res[3]:
                        extra = res[3]
                except LockTimeout as e:
                    # Bounded-wait contract: a wedged node lock answers
                    # 503 degraded, not a hung worker thread.
                    with api._stats_lock:
                        api.n_degraded_responses += 1
                    status, ctype, body = 503, "application/json", \
                        json.dumps({"state": "degraded",
                                    "error": str(e)}).encode()
                except Exception as e:            # noqa: BLE001
                    status, ctype, body = 500, "application/json", json.dumps(
                        {"error": str(e)}).encode()
                api._record_request(self.path, time.monotonic() - t0,
                                    status)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra.items():
                    self.send_header(k, v)
                if status == 405:
                    self.send_header("Allow", "POST")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # The SSE stream writes incrementally and owns its
                # socket until the bounded deadline — it cannot go
                # through the buffered one-body _dispatch path.
                route = self.path.split("?")[0].rstrip("/") or "/"
                qs = self.path.partition("?")[2]
                if route == "/map-events" and api.serving is not None \
                        and "mode=poll" not in qs:
                    api._serve_sse(self)
                    return
                self._dispatch("GET")

            def do_POST(self):
                # Drain any request body so keep-alive clients don't
                # desync the connection.
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self._dispatch("POST")

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- restart surface (launch.restart_mapper) -----------------------------

    def set_warming(self, warming: bool) -> None:
        """Open/close the staged warm-up serving window (the restart
        path's availability contract: answer stale, say so)."""
        self.warming = bool(warming)

    def rebind_mapper(self, mapper) -> None:
        """Swap the API onto a restarted MapperNode. The serving bundle
        is REBUILT around the new node — its tile-store snapshot
        closures and revision listener captured the old one, and a bare
        attribute swap would keep serving the destroyed node's final
        map forever. The event channel is carried over so connected
        /map-events clients keep their streams; the new mapper's
        `restart_epoch` (stamped into every /tiles response + ETag)
        tells delta clients to resync full instead of treating the
        resumed, possibly-older revision as a protocol violation."""
        self.mapper = mapper
        if self.serving is not None:
            from jax_mapping.serving import MapServing
            old = self.serving
            self.serving = MapServing(mapper.cfg.serving, mapper=mapper,
                                      voxel_mapper=self.voxel_mapper,
                                      events=old.events,
                                      pipeline=self.pipeline)
            # The voxel provider did NOT restart: carry its store over
            # like the event channel — a fresh store would re-hash and
            # re-encode every voxel tile for nothing (and reset its
            # serving counters).
            if old.voxel_store is not None:
                self.serving.voxel_store = old.voxel_store
            mapper.add_revision_listener(self.serving.on_map_revision)

    # -- bus callbacks ------------------------------------------------------

    def _map_cb(self, msg: OccupancyGrid) -> None:
        with self._lock:
            self._latest_map = msg

    def _frontiers_cb(self, msg: FrontierArray) -> None:
        with self._lock:
            self._latest_frontiers = msg

    # -- request statistics -------------------------------------------------

    #: Routes the per-route counter tracks individually; anything else
    #: aggregates under "other" so hostile paths can't grow label
    #: cardinality without bound.
    _KNOWN_ROUTES = frozenset((
        "/", "/start", "/stop", "/status", "/map-image", "/voxel-image",
        "/frontiers", "/metrics", "/save", "/load", "/goal",
        "/goal/cancel", "/save-map", "/tiles", "/voxel-tiles",
        "/map-events", "/trace"))

    def _record_request(self, path: str, elapsed_s: float,
                        status: int = 200) -> None:
        """One request's bookkeeping (any worker thread): total,
        per-route counter, latency histogram, 304 count — all under the
        dedicated stats lock (the unsynchronized `n_requests += 1` of
        the pre-serving handler lost increments under thread races)."""
        route = path.split("?")[0].rstrip("/") or "/"
        if route not in self._KNOWN_ROUTES:
            route = "other"
        with self._stats_lock:
            self.n_requests += 1
            self.route_requests[route] = \
                self.route_requests.get(route, 0) + 1
            if status == 304:
                self.n_304_responses += 1
            for k, le in enumerate(self._lat_buckets_s):
                if elapsed_s <= le:
                    self._lat_counts[k] += 1
                    break
            else:
                self._lat_counts[-1] += 1
            self._lat_sum_s += elapsed_s
            self._lat_n += 1

    @staticmethod
    def _etag_hit(headers, etag: str) -> bool:
        """RFC 7232 weak comparison, enough for our self-issued tags."""
        if headers is None:
            return False
        inm = headers.get("If-None-Match")
        if not inm:
            return False
        return etag in [v.strip() for v in inm.split(",")] or \
            inm.strip() == "*"

    # -- request handling ---------------------------------------------------

    def _dead_node_guard(self, route: str) -> Optional[Tuple[int, str, bytes]]:
        """503 degraded for routes whose backing node the supervisor has
        declared dead: /save against a dead mapper would checkpoint a
        frozen (possibly mid-crash) snapshot, /load and /goal would
        mutate state nobody is serving. Read-only routes keep answering
        — the cached map is exactly what an operator debugging the
        outage wants to see."""
        if self.supervisor is None:
            return None
        # /stop is deliberately NOT guarded: the safe-stop escape hatch
        # must work regardless of what the supervisor believes.
        needs = {"/save": "jax_mapper", "/load": "jax_mapper",
                 "/save-map": "jax_mapper", "/goal": "thymio_brain",
                 "/goal/cancel": "thymio_brain", "/start": "thymio_brain"}
        node = needs.get(route)
        if node is not None and not self.supervisor.is_alive(node):
            with self._stats_lock:
                self.n_degraded_responses += 1
            return 503, "application/json", json.dumps(
                {"state": "degraded",
                 "error": f"{node} is down (supervisor restart pending); "
                          f"{route} unavailable"}).encode()
        return None

    def handle(self, path: str, method: str = "GET",
               headers=None) -> Tuple:
        """Route a request; returns (status, content-type, body) or
        (status, content-type, body, extra-headers-dict). When causal
        tracing is armed the whole handler runs inside an `http:<route>`
        span, so goal publishes and checkpoint mutations made from HTTP
        chain under the request that caused them."""
        tracer = getattr(self.bus, "tracer", None)
        if tracer is None:
            return self._handle(path, method, headers)
        route = path.split("?")[0].rstrip("/") or "/"
        if route == "/trace":
            # The trace poller must not trace ITSELF: a span per poll
            # would advance the ring every request, so the /trace ETag
            # (keyed on the span seq) could never 304 and a tailing
            # poller would chase its own wake forever.
            return self._handle(path, method, headers)
        if route not in self._KNOWN_ROUTES:
            # Collapse like _record_request does: the tracer keys its
            # per-(parent, topic) seq table by span name, so raw
            # client-controlled paths would grow it without bound.
            route = "other"
        with tracer.span(f"http:{route}"):
            return self._handle(path, method, headers)

    def _handle(self, path: str, method: str = "GET",
                headers=None) -> Tuple:
        route = path.split("?")[0].rstrip("/") or "/"
        dead = self._dead_node_guard(route)
        if dead is not None:
            return dead
        if route == "/start":
            if self.brain is not None:
                self.brain.start_exploring()
            return 200, "application/json", \
                json.dumps({"status": "exploration started"}).encode()
        if route == "/stop":
            if self.brain is not None:
                self.brain.stop_exploring()
            return 200, "application/json", \
                json.dumps({"status": "exploration stopped"}).encode()
        if route == "/status":
            body = (self.brain.status(lock_timeout_s=self.lock_timeout_s)
                    if self.brain is not None else {})
            if self.warming:
                # Staged warm-up window: everything below is the PRIOR
                # epoch's picture, served instead of blocking while the
                # restarted mapper restores + pre-warms.
                body["state"] = "warming"
            if self.coldstart_status is not None:
                try:
                    body["cold_start"] = self.coldstart_status()
                except Exception:        # noqa: BLE001 — export only
                    pass
            if self.health is not None:
                # The whole degraded-mode picture in one glance: driver
                # link, per-robot OK/no_lidar/dead ladder, health clock.
                body["health"] = self.health.snapshot()
            if self.supervisor is not None:
                body["supervisor"] = self.supervisor.status()
            if self.recovery is not None:
                # The estimator-guardrail picture: per-robot watchdog
                # state/score, quarantine + relocalization progress,
                # anti-stuck ladder modes, live blacklist entries.
                body["recovery"] = self.recovery.snapshot()
                if self.mapper is not None:
                    body["recovery"]["n_scans_quarantined"] = \
                        self.mapper.n_scans_quarantined
                    body["recovery"]["n_relocalizations"] = \
                        self.mapper.n_relocalizations
            if self.mapper is not None:
                # Mapping-pipeline health alongside the brain's motion
                # fields — from the attached nodes directly, so every
                # stack with a mapper (sim, ros, rosbag-replay) gets the
                # operator's one-glance health check.
                body["n_scans_fused"] = self.mapper.n_scans_fused
                body["n_loops_closed"] = self.mapper.n_loops_closed
                if getattr(self.mapper, "cfg", None) is not None \
                        and self.mapper.cfg.decay.enabled:
                    # Map-healing observability (scenario engine): pass
                    # count + restart epoch, the lock-free counter
                    # convention.
                    body["decay"] = {
                        "n_passes": self.mapper.n_decay_passes,
                        "restart_epoch": self.mapper.restart_epoch,
                    }
                if hasattr(self.mapper, "match_stats"):
                    # Branch-and-bound matcher work accounting (last
                    # key match's candidate count + prune ratio).
                    body["match"] = self.mapper.match_stats()
                if hasattr(self.mapper, "frontier_stats"):
                    fs = self.mapper.frontier_stats()
                    if fs is not None:
                        # Incremental frontier pipeline: crop bbox,
                        # last recompute latency, tile-cache hit rate
                        # (ops/frontier_incremental.py).
                        body["frontier"] = fs
                calib = self.mapper.calibration()
                if calib is not None:
                    # Live odometry-scale re-measurement of the
                    # hand-calibrated SPEED_COEFF (report.pdf §III.D).
                    body["odom_calibration"] = calib
                if hasattr(self.mapper, "world_status"):
                    ws = self.mapper.world_status()
                    if ws is not None:
                        # Bounded-memory world (world/store.py): window
                        # origin/offset, eviction + rehydration
                        # counters, governor rung, spill-tier health.
                        body["world"] = ws
            if self.voxel_mapper is not None:
                body["n_images_fused"] = self.voxel_mapper.n_images_fused
                body["n_depth_keyframes"] = \
                    self.voxel_mapper.n_keyframes_stored
                body["n_voxel_refuses"] = self.voxel_mapper.n_refuses
            if self.planner is not None:
                body["n_plans"] = self.planner.n_plans
                body["plan_reachable"] = self.planner.last_reachable
                if self.planner.reachable_by_robot:
                    body["plan_reachable_by_robot"] = {
                        str(k): v for k, v in
                        self.planner.reachable_by_robot.items()}
            if self.devprof is not None:
                # Device-side performance picture (`/status.perf`):
                # per-function dispatch attribution, live recompile
                # counters, backend memory watermarks (None on CPU —
                # the graceful-None contract) and the cost-ledger
                # entries collected so far (collection is explicit:
                # CLI / gate / tests — never an HTTP side effect).
                body["perf"] = {
                    "dispatch": self.devprof.snapshot(),
                    "recompiles": self.devprof.recompiles(),
                    "memory": self.devprof.memory_stats(),
                    "cost_ledger": self.cost_ledger.snapshot(),
                    "cost_ledger_uncollected":
                        self.cost_ledger.n_uncollected(),
                }
            if self.tenancy is not None:
                # Mission multi-tenancy picture: per-tenant lifecycle
                # state, serving (epoch, revision) namespaces, bucket
                # capacity/occupancy and pad waste, admit/evict/
                # pre-warm counters (tenancy/controlplane.py).
                body["tenancy"] = self.tenancy.status()
            if self.pipeline is not None:
                # Freshness pipeline picture: pending/completed
                # revisions, windowed scan→served p99, last
                # install/delivery ticks (obs/pipeline.py).
                body["pipeline"] = self.pipeline.status()
            if self.slo is not None:
                # The freshness-budget picture (`/status.slo`): per
                # objective value vs threshold, fast/slow burn rates,
                # firing state, and the recent alert transitions.
                body["slo"] = self.slo.status()
            if self.extra_status is not None:
                body.update(self.extra_status())
            return 200, "application/json", json.dumps(body).encode()
        if route == "/map-image":
            return self._map_image(headers)
        if route == "/voxel-image":
            return self._voxel_image(headers)
        if route == "/tiles":
            return self._tiles(path, headers, source="grid")
        if route == "/voxel-tiles":
            return self._tiles(path, headers, source="voxel-height")
        if route == "/map-events":
            # The SSE variant is intercepted in the handler (it streams);
            # reaching here means ?mode=poll — one bounded long-poll.
            return self._map_events_poll(path)
        if route == "/frontiers":
            return self._frontiers()
        if route == "/metrics":
            return 200, "text/plain", self._metrics().encode()
        if route == "/trace":
            return self._trace(path, headers)
        if route in ("/save", "/load"):
            # Mutations are POST-only (ADVICE r3): GET /load from a link
            # prefetcher would silently replace the running map.
            if method != "POST":
                return 405, "application/json", json.dumps(
                    {"error": f"{route} requires POST "
                              f"(curl -X POST ...{route})"}).encode()
            return self._checkpoint(route, path)
        if route == "/goal":
            # Navigation goal dispatch without RViz: POST /goal?x=..&y=..
            # [&robot=N] — the HTTP twin of the SetGoal tool, addressed
            # like the namespaced goal topics. POST-only: a goal MOVES a
            # robot.
            if method != "POST":
                return 405, "application/json", json.dumps(
                    {"error": "/goal requires POST "
                              "(curl -X POST '.../goal?x=1&y=2')"}).encode()
            return self._set_goal(path)
        if route == "/goal/cancel":
            # The escape hatch RViz lacks: clear a manual goal (e.g. an
            # unreachable one) and let the robot go back to exploring.
            if method != "POST":
                return 405, "application/json", json.dumps(
                    {"error": "/goal/cancel requires POST"}).encode()
            if self.brain is None:
                return 404, "application/json", json.dumps(
                    {"error": "no brain attached"}).encode()
            q = parse_qs(urlparse(path).query)
            try:
                robot = int(q.get("robot", ["0"])[0])
            except (ValueError, IndexError):
                return 400, "application/json", json.dumps(
                    {"error": "robot must be an integer"}).encode()
            if not 0 <= robot < self.brain.n_robots:
                return 400, "application/json", json.dumps(
                    {"error": f"robot {robot} out of range"}).encode()
            had = self.brain.cancel_goal(robot)
            return 200, "application/json", json.dumps(
                {"status": "goal cancelled" if had else "no goal set",
                 "robot": robot}).encode()
        if route == "/save-map":
            # Writes to disk -> POST-only, same stance as /save.
            if method != "POST":
                return 405, "application/json", json.dumps(
                    {"error": "/save-map requires POST "
                              "(curl -X POST .../save-map)"}).encode()
            return self._save_rosmap(path)
        return 404, "application/json", \
            json.dumps({"error": f"no route {route}"}).encode()

    def _checkpoint(self, route: str, path: str) -> Tuple[int, str, bytes]:
        if self.mapper is None:
            return 404, "application/json", json.dumps(
                {"error": "no mapper attached"}).encode()
        from jax_mapping.io.checkpoint import (load_checkpoint,
                                               save_checkpoint)
        q = parse_qs(urlparse(path).query)
        name = os.path.basename(q.get("name", ["slam_state"])[0]) or \
            "slam_state"
        fp = os.path.join(self.checkpoint_dir, name + ".npz")
        if name.endswith((".voxel", ".voxelkf", ".prior", ".world")):
            # Reserved: checkpoint "x"'s sidecars live at "x.voxel.npz" /
            # "x.voxelkf.npz" / "x.prior.npz" / "x.world.npz"; a
            # checkpoint NAMED with any of those suffixes would collide
            # with them.
            return 400, "application/json", json.dumps(
                {"error": "checkpoint names ending in '.voxel', "
                          "'.voxelkf', '.prior' or '.world' are "
                          "reserved for sidecars"}).encode()
        # The LOGICAL config is what checkpoints record: in windowed
        # mode `mapper.cfg` is the window-sized derivation, and two
        # stacks with different logical extents share a window shape —
        # only the full config pins the world geometry the window
        # origin is anchored to. full_cfg == cfg when not windowed.
        cfg_json = getattr(self.mapper, "full_cfg",
                           self.mapper.cfg).to_json()
        if route == "/save":
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            states = self.mapper.snapshot_states()
            save_checkpoint(fp, states, config_json=cfg_json)
            body = {"status": "saved", "path": fp, "robots": len(states)}
            from jax_mapping.io.checkpoint import (clear_world_sidecar,
                                                   save_world_sidecar)
            world = getattr(self.mapper, "world", None)
            if world is not None:
                try:
                    body["world_path"] = save_world_sidecar(
                        fp, world.checkpoint_payload(),
                        config_json=cfg_json)
                except ValueError as e:
                    body["world_error"] = str(e)
            else:
                # A stale window manifest from an earlier windowed save
                # under this name would re-anchor a later windowed
                # resume at a dead origin.
                clear_world_sidecar(fp)
            prior = self.mapper.map_prior()
            from jax_mapping.io.checkpoint import (clear_prior_sidecar,
                                                   save_prior_sidecar)
            if prior is not None:
                try:
                    body["prior_path"] = save_prior_sidecar(
                        fp, prior, config_json=cfg_json)
                except ValueError as e:
                    # Same contract as the voxel sidecar: the main
                    # checkpoint IS saved; report the sidecar problem.
                    body["prior_error"] = str(e)
            else:
                # A stale sidecar from an earlier save under this name
                # would resurrect the OLD environment's prior on /load —
                # exactly what restore_states' clear contract prevents.
                # (Sentinel-checked: never deletes a non-sidecar file.)
                clear_prior_sidecar(fp)
            if self.voxel_mapper is not None:
                from jax_mapping.io.checkpoint import (
                    save_keyframe_sidecar, save_voxel_sidecar)
                try:
                    body["voxel_path"] = save_voxel_sidecar(
                        fp, self.voxel_mapper.snapshot_grid(),
                        config_json=cfg_json)
                    # Keyframe ring alongside, so post-/load closures can
                    # still repair the 3D map (the 2D scan ring's
                    # persistence, in 3D).
                    body["keyframe_path"] = save_keyframe_sidecar(
                        fp, self.voxel_mapper.snapshot_keyframes(),
                        config_json=cfg_json)
                except ValueError as e:
                    body["voxel_error"] = str(e)
            return 200, "application/json", json.dumps(body).encode()
        if not os.path.exists(fp):
            return 404, "application/json", json.dumps(
                {"error": f"no checkpoint {fp}"}).encode()
        from jax_mapping.models import slam as _S
        template = [_S.init_state(self.mapper.cfg)
                    for _ in self.mapper.states]
        states, saved_cfg_json = load_checkpoint(fp, template)
        from jax_mapping.config import configs_equivalent
        if saved_cfg_json is not None and \
                not configs_equivalent(saved_cfg_json, cfg_json):
            return 409, "application/json", json.dumps(
                {"error": "checkpoint config differs from the running "
                          "config; refusing to load"}).encode()
        # World-window sidecar (bounded-memory world): validate BEFORE
        # any restore mutates live state, same contract as the voxel
        # sidecar. A windowed checkpoint loaded into a non-windowed
        # stack already 409'd above (the state shapes differ).
        world = getattr(self.mapper, "world", None)
        world_payload = None
        if world is not None:
            from jax_mapping.io.checkpoint import load_world_sidecar
            try:
                world_payload = load_world_sidecar(
                    fp, running_config_json=cfg_json)
            except ValueError as e:
                return 409, "application/json", json.dumps(
                    {"error": f"world sidecar: {e}"}).encode()
        # Validate + read the 3D sidecar BEFORE any restore mutates live
        # state: a bad sidecar must 409 with everything untouched, not
        # leave the server half-restored.
        vgrid = None
        vkf = None
        if self.voxel_mapper is not None:
            from jax_mapping.io.checkpoint import (load_keyframe_sidecar,
                                                   load_voxel_sidecar,
                                                   voxel_sidecar_path)
            try:
                vgrid = load_voxel_sidecar(
                    fp, self.voxel_mapper.snapshot_grid(),
                    running_config_json=cfg_json)
                vkf = load_keyframe_sidecar(
                    fp, running_config_json=cfg_json)
                if vkf is not None:
                    self.voxel_mapper.validate_keyframes(vkf)
            except ValueError as e:
                return 409, "application/json", json.dumps(
                    {"error": f"voxel sidecar: {e}"}).encode()
        from jax_mapping.io.checkpoint import load_prior_sidecar
        try:
            prior = load_prior_sidecar(
                fp, self._G_empty(),
                running_config_json=cfg_json)
        except ValueError as e:
            return 409, "application/json", json.dumps(
                {"error": f"prior sidecar: {e}"}).encode()
        # No anchor poses: the /load contract is a server restart with
        # robots holding still, so checkpoint poses are still valid.
        # map_prior=None CLEARS a live prior — the checkpoint is the
        # source of truth now.
        if world is not None and world_payload is not None:
            # Re-anchor BEFORE the state install: the checkpointed
            # window grids are content AT the checkpointed origin, and
            # the install's revision bump + full dirty mark then serve
            # the re-anchored mosaic in one step.
            world.restore_payload(world_payload)
        self.mapper.restore_states(states, map_prior=prior)
        body = {"status": "loaded", "path": fp, "robots": len(states)}
        if world is not None and world_payload is not None:
            from jax_mapping.io.checkpoint import world_sidecar_path
            body["world_path"] = world_sidecar_path(fp)
            body["world_origin_tile"] = [int(v) for v in
                                         world_payload["origin_tile"]]
        if prior is not None:
            from jax_mapping.io.checkpoint import prior_sidecar_path
            body["prior_path"] = prior_sidecar_path(fp)
        if vgrid is not None:
            self.voxel_mapper.restore_grid(vgrid)
            body["voxel_path"] = voxel_sidecar_path(fp)
            if vkf is not None:
                # AFTER restore_grid (which clears the ring) and AFTER
                # restore_states (generations bumped): the graphs these
                # keyframes anchor to are exactly the restored ones.
                self.voxel_mapper.restore_keyframes(vkf)
                body["keyframes_restored"] = int(len(vkf["robot"]))
        return 200, "application/json", json.dumps(body).encode()

    def _set_goal(self, path: str) -> Tuple[int, str, bytes]:
        if self.brain is None:
            return 404, "application/json", json.dumps(
                {"error": "no brain attached"}).encode()
        from jax_mapping.bridge.brain import robot_ns
        from jax_mapping.bridge.messages import Pose2D
        q = parse_qs(urlparse(path).query)
        import math as _math
        try:
            x = float(q["x"][0])
            y = float(q["y"][0])
            robot = int(q.get("robot", ["0"])[0])
        except (KeyError, ValueError, IndexError):
            return 400, "application/json", json.dumps(
                {"error": "need numeric x and y (optional integer "
                          "robot)"}).encode()
        if not (_math.isfinite(x) and _math.isfinite(y)):
            # float('nan')/'inf' parse fine; the brain ingress also
            # rejects them, but the HTTP caller deserves a 400.
            return 400, "application/json", json.dumps(
                {"error": "x and y must be finite"}).encode()
        if self.mapper is not None:
            # An out-of-grid goal would clip to the border cell and plan
            # "reachable" toward a place that does not exist; refuse
            # with the valid extent so the caller can correct
            # (GridConfig.contains_m — the shared goal-ingress
            # predicate; x/y are already finite here, so a False means
            # out of extent).
            g = self.mapper.cfg.grid
            if not g.contains_m(x, y):
                ox, oy = g.origin_m
                span = g.extent_m
                return 400, "application/json", json.dumps(
                    {"error": f"goal outside the map extent "
                              f"[{ox}, {ox + span}) x [{oy}, {oy + span})"}
                ).encode()
        n = self.brain.n_robots
        if not 0 <= robot < n:
            return 400, "application/json", json.dumps(
                {"error": f"robot {robot} out of range (fleet of {n})"}
            ).encode()
        # Through the same bus topic the adapter and RViz use — ONE goal
        # ingress path, not a side channel.
        topic = "/goal_pose" if robot == 0 else \
            robot_ns(robot, n) + "goal_pose"
        self.bus.publisher(topic).publish(Pose2D(x, y, 0.0))
        return 200, "application/json", json.dumps(
            {"status": "goal set", "robot": robot,
             "x": x, "y": y}).encode()

    def _G_empty(self):
        """Template grid for the prior sidecar's shape/dtype check."""
        from jax_mapping.ops import grid as G
        return G.empty_grid(self.mapper.cfg.grid)

    def _save_rosmap(self, path: str) -> Tuple[int, str, bytes]:
        """POST /save-map?name=x -> checkpoint_dir/x.pgm + x.yaml in the
        ROS map_server format (the `map_saver_cli` artifact; the
        reference ecosystem's portable map interchange). Unlike /save,
        this is the LOSSY export every external consumer reads — npz
        checkpoints remain the lossless resume path."""
        if self.mapper is None:
            return 404, "application/json", json.dumps(
                {"error": "no mapper attached"}).encode()
        from jax_mapping.io import rosmap
        q = parse_qs(urlparse(path).query)
        name = os.path.basename(q.get("name", ["map"])[0]) or "map"
        g = self.mapper.cfg.grid
        # Threshold directly: the export edge's {-1, 0, 100} trichotomy
        # (occupancy_from_logodds semantics) without a message detour.
        lo = np.asarray(self.mapper.merged_grid())
        occ = np.full(lo.shape, -1, np.int8)
        occ[lo <= g.free_threshold] = 0
        occ[lo >= g.occ_threshold] = 100
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        pgm, yaml = rosmap.save_map(
            os.path.join(self.checkpoint_dir, name),
            occ, g.resolution_m, g.origin_m)
        return 200, "application/json", json.dumps(
            {"status": "saved", "pgm": pgm, "yaml": yaml}).encode()

    def _map_image(self, headers=None) -> Tuple:
        with self._lock:
            msg = self._latest_map
        if msg is None:
            # Reference guard (`server/.../main.py:244-245`).
            return 404, "application/json", \
                json.dumps({"error": "map not yet available"}).encode()
        # Conditional GET keyed on the map stamp: a poller holding the
        # current ETag pays a 304 header instead of the full PNG body —
        # the byte-saving half of the cache even before the tile path.
        etag = f'W/"map-{msg.header.stamp}"'
        # Revision-age header on the whole-PNG route too: the legacy
        # polling client measures the same server-monotonic staleness
        # the tile clients do (the /map message trails the live grid
        # by up to a publish period — the age reports the newest
        # INSTALLED revision, the freshness a poller could have).
        timing = self._timing_header(None)
        if self._etag_hit(headers, etag):
            return 304, "image/png", b"", {"ETag": etag, **timing}
        data = self._cached_png(
            "map", msg.header.stamp,
            lambda: png_codec.encode_gray(msg.as_image_array()))
        return 200, "image/png", data, {"ETag": etag, **timing}

    def _voxel_image(self, headers=None) -> Tuple:
        """Grayscale height-map PNG of the 3D voxel map (0 = unmapped
        column, brighter = taller top surface) — the /map-image analog
        for the BASELINE configs[4] pipeline, with the same cache policy
        (keyed on fusion progress: re-encoding an unchanged grid for a
        polling UI is the exact waste the map-image cache exists for)."""
        if self.voxel_mapper is None:
            return 404, "application/json", json.dumps(
                {"error": "no voxel mapper attached (run the stack with "
                          "depth_cam enabled)"}).encode()
        key = (self.voxel_mapper.n_images_fused,
               self.voxel_mapper.map_revision)
        etag = f'W/"voxel-{key[0]}-{key[1]}"'
        if self._etag_hit(headers, etag):
            return 304, "image/png", b"", {"ETag": etag}
        data = self._cached_png(
            "voxel", key,
            lambda: png_codec.encode_gray(
                self.voxel_mapper.height_map_image()))
        return 200, "image/png", data, {"ETag": etag}

    # -- serving: tiled delta distribution (serving/) ------------------------

    def _tiles(self, path: str, headers, source: str) -> Tuple:
        """GET /tiles?since=<revision>[&level=k] — the delta protocol:
        refresh the tile store to the mapper's revision, then return
        ONLY the tiles stamped newer than the client's `since` as
        base64 PNGs in a JSON manifest. since=-1 (or omitted) is the
        initial full snapshot. ETag on the store revision, so a poller
        that is already current pays a 304."""
        q = parse_qs(urlparse(path).query)
        tenant = q.get("tenant", [None])[0]
        if tenant is not None:
            # Per-tenant delta session (tenancy/): the tenant's OWN
            # (epoch, revision) namespace replaces the mapper's — a
            # resumed mission's epoch bump invalidates pre-suspend
            # ETags exactly like a supervisor restart does for the
            # shared map, and co-tenant churn never touches it.
            if self.tenancy is None:
                return 404, "application/json", json.dumps(
                    {"error": "no tenant control plane attached "
                              "(TenancyConfig.enabled=False)"}).encode()
            try:
                store = self.tenancy.tile_store(tenant)
            except (KeyError, ValueError) as e:
                return 404, "application/json", json.dumps(
                    {"error": str(e)}).encode()
            source = f"tenant:{tenant}"
        else:
            if self.serving is None:
                return 404, "application/json", json.dumps(
                    {"error": "serving disabled "
                              "(ServingConfig.enabled=False)"}).encode()
            store = self.serving.store(source)
            if store is None:
                return 404, "application/json", json.dumps(
                    {"error": f"no {source} tile store (run the stack "
                              "with the producing mapper "
                              "attached)"}).encode()
        try:
            since = int(q.get("since", ["-1"])[0])
            level = int(q["level"][0]) if "level" in q else None
        except (ValueError, IndexError):
            return 400, "application/json", json.dumps(
                {"error": "since and level must be integers"}).encode()
        try:
            store.refresh()
        except (KeyError, ValueError) as e:
            # A tenant evicted between store lookup and refresh: its
            # snapshot has no state to serve anymore.
            return 404, "application/json", json.dumps(
                {"error": str(e)}).encode()
        rev, entries, meta = store.tiles_since(since, level)
        # Restart epoch in body AND ETag: a supervisor restart-resume
        # (or a tenant evict→re-admit) legitimately re-serves an older
        # revision; clients key cache validity on (epoch, revision),
        # not revision alone — a stale pre-restart ETag can never 304
        # against the resumed store. Read AFTER the refresh on both
        # paths: an epoch captured before it could stamp fresh content
        # with the PRIOR epoch and match a stale client's ETag.
        if tenant is not None:
            try:
                epoch = self.tenancy.epoch(tenant)
            except KeyError:
                return 404, "application/json", json.dumps(
                    {"error": f"unknown tenant {tenant!r}"}).encode()
        else:
            epoch = self.serving.epoch(source)
        # The warming flag is part of the REPRESENTATION (body and ETag
        # must agree — the /trace doctrine): a poller current on the
        # steady-state tag still learns the window opened, and a cached
        # warming body can never 304 past the window's end. The
        # quarantine stamp follows the same doctrine: a quarantined
        # tenant keeps serving its frozen last-good revision, but the
        # body and tag both say so — a client current on the healthy
        # tag re-fetches once and learns the state, and a cached
        # quarantined body can never 304 past the re-admission (whose
        # epoch bump changes the tag anyway).
        warming = self.warming
        quarantined = (tenant is not None
                       and self.tenancy.tenant_lifecycle(tenant)
                       == "quarantined")
        suffix = ('-warming' if warming else '') + \
            ('-quarantined' if quarantined else '')
        # Bounded-memory world: the eviction epoch rides the ETag so a
        # validator can never 304 across an eviction-state flip whose
        # content change is exactly "these tiles became markers".
        wepoch = getattr(store, "evicted_epoch", 0)
        if wepoch:
            suffix += f"-w{wepoch}"
        etag = f'W/"{source}-e{epoch}-r{rev}{suffix}"'
        # First-client-delivery waypoint + Server-Timing revision age:
        # a 304 confirms freshness exactly as a body does (the client
        # HOLDS the revision), so both answers stamp and both carry
        # the age header. Grid + tenant surfaces only — they own the
        # freshness chain; the voxel overview rides outside it.
        timing = {}
        if self.pipeline is not None and rev >= 0 \
                and (tenant is not None or source == "grid"):
            # Epoch threaded through: a restart/re-admission resets
            # the ledger's delivered mark so the staleness objective
            # tracks the NEW epoch's numbering instead of going blind
            # until it outgrows the old mark.
            self.pipeline.delivered(rev, tenant=tenant or "",
                                    epoch=epoch)
            timing = self._timing_header(rev, tenant=tenant or "")
        if self._etag_hit(headers, etag):
            return 304, "application/json", b"", \
                {"ETag": etag, **timing}
        body = dict(meta)
        body.update({"revision": rev, "since": since, "epoch": epoch,
                     "tiles": entries})
        if warming:
            # Staged warm-up: these tiles are the PRIOR epoch's content
            # (the restarted node hasn't entered service yet) — valid,
            # stamped, and explicitly stale.
            body["state"] = "warming"
        if quarantined:
            # Containment: the frozen last-good revision of a
            # quarantined tenant — valid, stamped, and explicitly not
            # advancing until a re-admission probe passes.
            body["state"] = "quarantined"
        return 200, "application/json", json.dumps(body).encode(), \
            {"ETag": etag, **timing}

    def _timing_header(self, revision: Optional[int],
                       tenant: str = "") -> Dict[str, str]:
        """`Server-Timing: rev;desc=..., age;dur=<ms>` for a response
        serving `revision` (None = the newest installed): the age is a
        SERVER monotonic delta since the revision's install, so a
        client measures observed staleness without clock trust. Empty
        when no ledger is armed or the revision predates it — better
        no header than a fabricated age."""
        if self.pipeline is None:
            return {}
        age = self.pipeline.revision_age_ms(revision, tenant=tenant)
        if age is None:
            return {}
        rev_desc = "latest" if revision is None else str(revision)
        return {"Server-Timing":
                f'rev;desc="{rev_desc}", age;dur={age:.1f}'}

    def _map_events_poll(self, path: str) -> Tuple[int, str, bytes]:
        """GET /map-events?mode=poll&since=R[&wait_s=S] — bounded
        long-poll: answers as soon as the map revision exceeds `since`
        (immediately when it already does), or after the capped wait
        with `timed_out: true`. The worker thread's wait is bounded by
        `ServingConfig.event_wait_max_s` — the 503-degraded path's
        bounded-wait contract, applied to push."""
        if self.serving is None or self.mapper is None:
            return 404, "application/json", json.dumps(
                {"error": "serving disabled"}).encode()
        q = parse_qs(urlparse(path).query)
        try:
            since = int(q.get("since", ["-1"])[0])
            wait_s = float(q.get("wait_s", ["10"])[0])
        except (ValueError, IndexError):
            return 400, "application/json", json.dumps(
                {"error": "since must be an integer, wait_s a "
                          "number"}).encode()
        wait_s = max(0.0, min(wait_s, self.serving.cfg.event_wait_max_s))
        # Subscribe BEFORE the current-revision check (the _serve_sse
        # order): an event fanned out between a check and a later
        # subscribe would be missed and the poll would ride out its
        # whole capped wait for an advance that already happened.
        sub = self.serving.events.subscribe()
        try:
            current = self.mapper.serving_revision()
            if current > since:
                return 200, "application/json", json.dumps(
                    {"map": "grid", "revision": current,
                     "timed_out": False}).encode(), \
                    self._timing_header(current)
            deadline = time.monotonic() + wait_s
            while not self._shutting_down.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                ev = sub.next(min(0.5, remaining))
                if ev is not None and int(ev.get("revision", -1)) > since:
                    rev = int(ev["revision"])
                    return 200, "application/json", json.dumps(
                        {"map": "grid", "revision": rev,
                         "timed_out": False}).encode(), \
                        self._timing_header(rev)
        finally:
            self.serving.events.unsubscribe(sub)
        current = self.mapper.serving_revision()
        return 200, "application/json", json.dumps(
            {"map": "grid", "revision": current,
             "timed_out": True}).encode(), self._timing_header(current)

    def _serve_sse(self, handler) -> None:
        """GET /map-events — Server-Sent Events stream of map-revision
        advances, written directly on the handler's socket.

        Backpressure and bounds: each client owns ONE bounded queue
        (drop-to-latest on overflow — revisions are cumulative, old
        events carry no information the newest doesn't), the stream
        lifetime is capped by `event_wait_max_s` (clients reconnect,
        standard SSE), the per-connection socket timeout covers stalled
        writes, and shutdown closes every subscription — a slow client
        can never pin server memory or a worker thread."""
        self._record_request(handler.path, 0.0)
        q = parse_qs(urlparse(handler.path).query)
        try:
            since = int(q.get("since", ["-1"])[0])
            max_s = float(q.get("timeout_s",
                                [str(self.serving.cfg.event_wait_max_s)])[0])
        except (ValueError, IndexError):
            since, max_s = -1, self.serving.cfg.event_wait_max_s
        max_s = max(0.0, min(max_s, self.serving.cfg.event_wait_max_s))
        sub = self.serving.events.subscribe()
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            for k, v in self._timing_header(None).items():
                # Stream-start revision age (the newest installed):
                # SSE headers go out once; per-event freshness rides
                # the revision numbers in the events themselves.
                handler.send_header(k, v)
            handler.end_headers()
            last_sent = since
            current = (self.mapper.serving_revision()
                       if self.mapper is not None else -1)
            if current > last_sent:
                handler.wfile.write(
                    b"event: map\ndata: " + json.dumps(
                        {"map": "grid", "revision": current}).encode()
                    + b"\n\n")
                last_sent = current
            deadline = time.monotonic() + max_s
            while not self._shutting_down.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                ev = sub.next(min(0.5, remaining))
                if ev is None:
                    handler.wfile.write(b": keepalive\n\n")
                    continue
                rev = int(ev.get("revision", -1))
                if rev <= last_sent:
                    continue       # drop-to-latest may reorder history
                handler.wfile.write(
                    b"event: map\ndata: "
                    + json.dumps({"map": ev.get("map", "grid"),
                                  "revision": rev}).encode() + b"\n\n")
                last_sent = rev
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass                   # client went away: nothing to salvage
        finally:
            self.serving.events.unsubscribe(sub)

    def _cached_png(self, name: str, key, render: Callable[[], bytes]
                    ) -> bytes:
        """ONE cache policy for every PNG route (map, voxel): serve the
        cached bytes while the content key matches within png_cache_s;
        render outside the lock (a worst-case race costs one redundant
        encode, never a stale serve — the key check gates every hit).
        Hits count both per-route (`png_cache_hits` dict) and in the
        historical total `n_png_cache_hits`."""
        now = time.monotonic()
        with self._lock:
            ent = self._png_cache.get(name)
            if ent is not None and now - ent[1] < self.png_cache_s \
                    and ent[2] == key:
                self.n_png_cache_hits += 1
                self.png_cache_hits[name] = \
                    self.png_cache_hits.get(name, 0) + 1
                return ent[0]
        data = render()
        with self._lock:
            self._png_cache[name] = (data, time.monotonic(), key)
        return data

    def _trace(self, path: str, headers=None) -> Tuple:
        """GET /trace?since=<seq> — the tracer's span ring as Chrome-
        trace/Perfetto events, incrementally: only spans whose monotone
        `seq` stamp exceeds `since` (omitted/0 = everything still in
        the ring), plus `next` to pass as the following poll's `since`
        — a poller tails the live trace without re-downloading the
        ring. 404 when tracing is off (`ObsConfig.enabled=False`), the
        /tiles-when-serving-disabled convention.

        Conditional GETs ride the /tiles discipline: the ETag is the
        span-ring head seq, READ BEFORE the span content (lint C1 —
        the reverse order could stamp newer spans with an older seq and
        a matching If-None-Match would 304 away spans the client never
        saw), and the returned window is CAPPED at that seq so body and
        ETag always agree; an empty window costs a 304 header, not a
        JSON body."""
        tracer = getattr(self.bus, "tracer", None)
        if tracer is None:
            return 404, "application/json", json.dumps(
                {"error": "tracing disabled (ObsConfig.enabled=False)"}
            ).encode()
        from jax_mapping.obs.export import chrome_events
        q = parse_qs(urlparse(path).query)
        try:
            since = int(q.get("since", ["0"])[0])
        except (ValueError, IndexError):
            return 400, "application/json", json.dumps(
                {"error": "since must be an integer span seq"}).encode()
        head = tracer.last_seq()           # revision BEFORE content (C1)
        etag = f'W/"trace-{head}-s{since}"'
        if self._etag_hit(headers, etag):
            return 304, "application/json", b"", {"ETag": etag}
        spans = [s for s in tracer.spans_since(since)
                 if s["seq"] <= head]
        return 200, "application/json", json.dumps(
            {"traceEvents": chrome_events(spans),
             "next": spans[-1]["seq"] if spans else since}).encode(), \
            {"ETag": etag}

    def _frontiers(self) -> Tuple[int, str, bytes]:
        with self._lock:
            fr = self._latest_frontiers
        if fr is None:
            return 404, "application/json", \
                json.dumps({"error": "frontiers not yet available"}).encode()
        body = {
            "targets_xy": np.asarray(fr.targets_xy).tolist(),
            "sizes": np.asarray(fr.sizes).tolist(),
            "assignment": np.asarray(fr.assignment).tolist(),
        }
        return 200, "application/json", json.dumps(body).encode()

    def _metrics(self) -> str:
        return self._registry.render()

    def _build_metrics_registry(self):
        """Declare the `/metrics` exposition (obs/registry.py).

        Registration order IS exposition order and every value keeps
        its historical format string, so the registry reproduces the
        hand-assembled pre-obs document BYTE-for-byte for every family
        that existed before it (pinned by tests/test_obs.py +
        test_scenarios.py) — dashboards and scrape configs survive the
        refactor. New families (bus subscription health, stage-latency
        histograms, obs counters) append after the historical tail.
        Collectors returning None omit their family — the conditional-
        subsystem pattern (`if self.brain is not None: lines += ...`)
        as data."""
        from jax_mapping.obs.registry import (Family, MetricsRegistry,
                                              histogram_samples,
                                              summary_samples)
        reg = MetricsRegistry()
        reg.family("jax_mapping_http_requests_total", "counter",
                   lambda: [("", str(self.n_requests))])
        reg.family("jax_mapping_png_cache_hits_total", "counter",
                   lambda: [("", str(self.n_png_cache_hits))])

        def brain_families():
            if self.brain is None:
                return None
            st = self.brain.status(lock_timeout_s=self.lock_timeout_s)
            return (
                Family("jax_mapping_brain_ticks_total", "counter",
                       (("", str(st.get("ticks", 0))),)),
                Family("jax_mapping_brain_io_errors_total", "counter",
                       (("", str(st.get("io_errors", 0))),)),
                Family("jax_mapping_brain_connected", "gauge",
                       (("", str(int(bool(st.get("connected"))))),)),
            )
        reg.add_source(brain_families)

        def health_families():
            if self.health is None:
                return None
            # Degraded-mode ladder as gauges: ok=0 no_lidar=1 dead=2
            # per robot (estimator_diverged=3 — a distinct severity,
            # not a silence rung), driver ok=0 offline=1 recovering=2 —
            # thresholdable without string parsing.
            snap = self.health.snapshot()
            rank = {"ok": 0, "no_lidar": 1, "dead": 2,
                    "estimator_diverged": 3,
                    "offline": 1, "recovering": 2}
            return (
                Family("jax_mapping_health_robot_state", "gauge",
                       tuple((f'{{robot="{i}"}}', str(rank.get(s, 0)))
                             for i, s in enumerate(snap["robots"]))),
                Family("jax_mapping_health_driver_state", "gauge",
                       (("", str(rank.get(snap["driver"], 0))),)),
                Family("jax_mapping_health_transitions_total", "counter",
                       (("", str(snap["n_transitions"])),)),
            )
        reg.add_source(health_families)

        def supervisor_families():
            if self.supervisor is None:
                return None
            sup = self.supervisor.status()
            return (
                Family("jax_mapping_supervisor_dead_nodes", "gauge",
                       (("", str(len(sup["dead"]))),)),
                Family("jax_mapping_supervisor_restarts_total", "counter",
                       (("", str(sum(sup["restarts"].values()))),)),
                Family("jax_mapping_supervisor_checkpoints_total",
                       "counter", (("", str(sup["checkpoints"])),)),
            )
        reg.add_source(supervisor_families)

        def match_families():
            # Branch-and-bound matcher work accounting (SlamDiag
            # match_candidates/prune_ratio): evaluations the last key
            # match scored per robot, and the fraction pruned off the
            # exhaustive sweep.
            if self.mapper is None \
                    or not hasattr(self.mapper, "match_stats"):
                return None
            ms = self.mapper.match_stats()
            return (
                Family("jax_mapping_match_candidates", "gauge",
                       tuple((f'{{robot="{i}"}}', str(c))
                             for i, c in enumerate(ms["candidates"]))),
                Family("jax_mapping_match_prune_ratio", "gauge",
                       tuple((f'{{robot="{i}"}}', str(r))
                             for i, r in enumerate(ms["prune_ratio"]))),
            )
        reg.add_source(match_families)

        def frontier_families():
            # Incremental frontier publish pipeline
            # (ops/frontier_incremental.py): recompute-vs-skip split,
            # tile coarse-mask cache traffic, live crop size.
            fs = (self.mapper.frontier_stats()
                  if self.mapper is not None
                  and hasattr(self.mapper, "frontier_stats") else None)
            if fs is None:
                return None
            # Recompute latency is NOT a hand-built gauge here any
            # more (ISSUE 10 satellite): the pipeline records each
            # recompute into the `frontier.recompute` stage, so it
            # reports through the one stage mechanism — the
            # `jax_mapping_stage_frontier_recompute_ms` summary and
            # `..._seconds` fixed log-bucket histogram families below.
            # `/status.frontier.last_recompute_ms` keeps the one-glance
            # number.
            return [
                Family("jax_mapping_frontier_recompute_total", "counter",
                       (("", str(fs["n_recomputes"])),)),
                Family("jax_mapping_frontier_skip_total", "counter",
                       (("", str(fs["n_skips"])),)),
                Family("jax_mapping_frontier_cache_hits_total", "counter",
                       (("", str(fs["cache_hits"])),)),
                Family("jax_mapping_frontier_cache_misses_total",
                       "counter", (("", str(fs["cache_misses"])),)),
                Family("jax_mapping_frontier_crop_cells", "gauge",
                       (("", str(fs["crop_cells"])),)),
            ]
        reg.add_source(frontier_families)

        def planner_families():
            if self.planner is None \
                    or not hasattr(self.planner, "n_overlay_rebuilds"):
                return None
            return (
                Family("jax_mapping_planner_overlay_rebuilds_total",
                       "counter",
                       (("", str(self.planner.n_overlay_rebuilds)),)),
                Family("jax_mapping_planner_overlay_reuses_total",
                       "counter",
                       (("", str(self.planner.n_overlay_reuses)),)),
            )
        reg.add_source(planner_families)

        def recovery_families():
            if self.recovery is None:
                return None
            rec = self.recovery.snapshot()
            wd = rec["watchdog"]
            fams = [
                Family("jax_mapping_recovery_estimator_score", "gauge",
                       tuple((f'{{robot="{i}"}}', str(s))
                             for i, s in enumerate(wd["scores"]))),
                Family("jax_mapping_recovery_diverge_events_total",
                       "counter", (("", str(wd["n_diverge_events"])),)),
                Family("jax_mapping_recovery_readmits_total", "counter",
                       (("", str(wd["n_readmits"])),)),
                Family("jax_mapping_recovery_reloc_attempts_total",
                       "counter",
                       (("", str(rec["relocalization"]["n_attempts"])),)),
                Family("jax_mapping_recovery_reloc_verified_total",
                       "counter",
                       (("", str(rec["relocalization"]["n_verified"])),)),
                Family("jax_mapping_recovery_stuck_detections_total",
                       "counter",
                       (("", str(rec["antistuck"]["n_stuck_detections"])),
                        )),
                Family("jax_mapping_recovery_blacklisted_total",
                       "counter",
                       (("", str(rec["blacklist"]["n_blacklisted"])),)),
            ]
            pc = rec["relocalization"].get("pyramid_cache")
            if pc is not None:
                # Revision-keyed pyramid cache feeding the pruned
                # wide-window relocalizer (ops/pyramid.PyramidCache).
                fams += [
                    Family("jax_mapping_match_pyramid_cache_hits_total",
                           "counter", (("", str(pc["n_hits"])),)),
                    Family("jax_mapping_match_pyramid_cache_misses_total",
                           "counter", (("", str(pc["n_misses"])),)),
                    Family("jax_mapping_match_pyramid_cache_hit_rate",
                           "gauge", (("", f"{pc['hit_rate']:.4f}"),)),
                ]
            return fams
        reg.add_source(recovery_families)

        def http_stats_families():
            # Request-serving telemetry: per-route counters + the
            # latency histogram, snapshotted under the stats lock ONCE
            # so the exposition is internally consistent (bucket counts
            # sum to _count).
            with self._stats_lock:
                routes = dict(self.route_requests)
                lat_counts = list(self._lat_counts)
                lat_sum = self._lat_sum_s
                lat_n = self._lat_n
                n_304 = self.n_304_responses
            return (
                Family("jax_mapping_http_requests_by_route_total",
                       "counter",
                       tuple((f'{{route="{r}"}}', str(n))
                             for r, n in sorted(routes.items()))),
                Family("jax_mapping_http_request_seconds", "histogram",
                       tuple(histogram_samples(
                           self._lat_buckets_s, lat_counts, lat_sum,
                           lat_n))),
                Family("jax_mapping_http_not_modified_total", "counter",
                       (("", str(n_304)),)),
            )
        reg.add_source(http_stats_families)

        def serving_families():
            if self.serving is None:
                return None
            # Tile-store + event-channel health: hit-rates and
            # backpressure drops for the delta-serving path.
            sstats = self.serving.stats()
            fams = []
            for src in ("grid", "voxel"):
                st = sstats.get(src)
                if st is None:
                    continue
                fams += [
                    Family(f"jax_mapping_serving_{src}_revision", "gauge",
                           (("", str(st["revision"])),)),
                    Family(f"jax_mapping_serving_{src}_tiles_encoded"
                           "_total", "counter",
                           (("", str(st["n_tiles_encoded"])),)),
                    Family(f"jax_mapping_serving_{src}_tiles_clean"
                           "_total", "counter",
                           (("", str(st["n_tiles_clean_skipped"])),)),
                    Family(f"jax_mapping_serving_{src}_hint_missed"
                           "_total", "counter",
                           (("", str(st["n_hint_missed"])),)),
                ]
            ev = sstats["events"]
            fams += [
                Family("jax_mapping_serving_event_clients", "gauge",
                       (("", str(ev["n_clients"])),)),
                Family("jax_mapping_serving_events_total", "counter",
                       (("", str(ev["n_events"])),)),
                Family("jax_mapping_serving_events_dropped_total",
                       "counter", (("", str(ev["n_dropped"])),)),
            ]
            return fams
        reg.add_source(serving_families)

        def world_families():
            # Bounded-memory world (world/store.py): the governor rung
            # + pressure, tier occupancies, eviction/rehydration and
            # integrity counters — the memory-chaos observables.
            if self.mapper is None \
                    or not hasattr(self.mapper, "world_status"):
                return None
            ws = self.mapper.world_status()
            if ws is None:
                return None
            gov = ws.get("governor", {})
            fams = [
                Family("jax_mapping_world_shifts_total", "counter",
                       (("", str(ws["shifts"])),)),
                Family("jax_mapping_world_evictions_total", "counter",
                       (("", str(ws["evictions"])),)),
                Family("jax_mapping_world_rehydrated_host_total",
                       "counter", (("", str(ws["rehydrated_host"])),)),
                Family("jax_mapping_world_rehydrated_disk_total",
                       "counter", (("", str(ws["rehydrated_disk"])),)),
                Family("jax_mapping_world_tiles_lost_total", "counter",
                       (("", str(ws["lost_tiles"])),)),
                Family("jax_mapping_world_corrupt_spills_total",
                       "counter", (("", str(ws["corrupt_spills"])),)),
                Family("jax_mapping_world_host_tiles", "gauge",
                       (("", str(ws["host_tiles"])),)),
                Family("jax_mapping_world_away_tiles", "gauge",
                       (("", str(ws["away_tiles"])),)),
                Family("jax_mapping_world_device_window_bytes", "gauge",
                       (("", str(ws["device_window_bytes"])),)),
                Family("jax_mapping_world_governor_rung", "gauge",
                       (("", str(gov.get("rung", 0))),)),
                Family("jax_mapping_world_governor_pressure", "gauge",
                       (("", str(gov.get("pressure", 0.0))),)),
                Family("jax_mapping_world_governor_refused_total",
                       "counter", (("", str(gov.get("refused", 0))),)),
            ]
            spill = ws.get("spill")
            if spill is not None:
                fams += [
                    Family("jax_mapping_world_spill_tiles", "gauge",
                           (("", str(spill["tiles"])),)),
                    Family("jax_mapping_world_spill_corrupt_reads_total",
                           "counter",
                           (("", str(spill["corrupt_reads"])),)),
                ]
            return fams
        reg.add_source(world_families)

        def degraded_samples():
            with self._stats_lock:
                return [("", str(self.n_degraded_responses))]
        reg.family("jax_mapping_http_degraded_responses_total", "counter",
                   degraded_samples)
        reg.family("jax_mapping_bus_partition_dropped_total", "counter",
                   lambda: [("", str(self.bus.n_partition_dropped))])

        def global_counter_families():
            # Process-wide registry (utils/profiling.py): event counters
            # fed by the mapper/brain loops.
            from jax_mapping.utils import global_metrics
            return tuple(
                Family("jax_mapping_" + name.replace(".", "_") + "_total",
                       "counter", (("", str(val)),))
                for name, val in
                sorted(global_metrics.counters.snapshot().items()))
        reg.add_source(global_counter_families)

        def stage_families():
            # Valid exposition: the summary family carries only
            # _sum/_count; derived series are their own gauges.
            from jax_mapping.utils import global_metrics
            fams = []
            for name, st_ in sorted(
                    global_metrics.stages.snapshot().items()):
                base = "jax_mapping_stage_" + name.replace(".", "_")
                fams += [
                    Family(f"{base}_ms", "summary",
                           tuple(summary_samples(st_["count"],
                                                 st_["sum_ms"]))),
                    Family(f"{base}_ms_mean", "gauge",
                           (("", f"{st_['mean_ms']:.3f}"),)),
                    Family(f"{base}_ms_ewma", "gauge",
                           (("", f"{st_['ewma_ms']:.3f}"),)),
                    Family(f"{base}_ms_max", "gauge",
                           (("", f"{st_['max_ms']:.3f}"),)),
                ]
            return fams
        reg.add_source(stage_families)

        # ---- new FAMILY SOURCES (obs tier) register after the
        # historical ones, so their families render after the
        # historical tail. (Names/types/formats of historical families
        # are byte-compatible; the stage block above is NOT a frozen
        # prefix — it renders sorted over whatever stages have been
        # recorded, and the new always-on stages (brain.tick, ...)
        # interleave into that sort, exactly as a newly-recorded stage
        # always did pre-obs.) ----------------------------------------

        def bus_families():
            # Per-subscription bus health by topic (ISSUE 9 satellite):
            # the drop counters bridge/bus.py always recorded but never
            # exported, plus live queue depth — a silently lossy or
            # backed-up topic becomes a dashboard fact.
            stats = self.bus.subscription_stats()
            if not stats:
                return None
            return (
                Family("jax_mapping_bus_subscription_queue_depth",
                       "gauge",
                       tuple((f'{{topic="{t}"}}', str(s["queue_depth"]))
                             for t, s in stats.items())),
                Family("jax_mapping_bus_subscription_received_total",
                       "counter",
                       tuple((f'{{topic="{t}"}}', str(s["n_received"]))
                             for t, s in stats.items())),
                Family("jax_mapping_bus_subscription_dropped_total",
                       "counter",
                       tuple((f'{{topic="{t}"}}', str(s["n_dropped"]))
                             for t, s in stats.items())),
            )
        reg.add_source(bus_families)

        def stage_histogram_families():
            # Fixed log-bucket latency histograms per stage
            # (utils/profiling.HIST_EDGES_S): mapper tick, match, fuse,
            # publish_frontiers, serving snapshot, ... — every stage
            # gets the same bucket grid so runs compare bucket-for-
            # bucket.
            from jax_mapping.utils import global_metrics
            return tuple(
                Family("jax_mapping_stage_" + name.replace(".", "_")
                       + "_seconds", "histogram",
                       tuple(histogram_samples(
                           h["edges_s"], h["buckets"], h["sum_s"],
                           h["count"])))
                for name, h in sorted(
                    global_metrics.stages.histograms().items()))
        reg.add_source(stage_histogram_families)

        def obs_families():
            from jax_mapping.obs.recorder import flight_recorder
            rs = flight_recorder.stats()
            fams = [
                Family("jax_mapping_obs_recorder_events_total", "counter",
                       (("", str(rs["n_events"])),)),
                Family("jax_mapping_obs_recorder_dumps_total", "counter",
                       (("", str(rs["n_dumps"])),)),
            ]
            tracer = getattr(self.bus, "tracer", None)
            if tracer is not None:
                fams.append(Family("jax_mapping_obs_trace_spans_total",
                                   "counter",
                                   (("", str(tracer.last_seq())),)))
            return fams
        reg.add_source(obs_families)

        def devprof_families():
            # Device-side dispatch attribution (obs/devprof.py): call
            # counts + blocked-on-dispatch wall-time histograms per
            # jitted entry point (ONE family sliced by fn label, the
            # HIST_EDGES_S grid — runs compare bucket-for-bucket),
            # runtime recompile counters, and backend memory
            # watermarks where the backend provides them (whole family
            # omitted on CPU — graceful None).
            if self.devprof is None:
                return None
            from jax_mapping.obs.registry import (
                labeled_histogram_samples)
            hists = self.devprof.histograms()
            recs = self.devprof.recompiles()
            fams = [
                Family("jax_mapping_device_dispatch_total", "counter",
                       tuple((f'{{fn="{fn}"}}', str(h["count"]))
                             for fn, h in hists.items())),
                Family("jax_mapping_device_dispatch_seconds",
                       "histogram",
                       tuple(s for fn, h in hists.items()
                             for s in labeled_histogram_samples(
                                 f'fn="{fn}"', h["edges_s"],
                                 h["buckets"], h["sum_s"],
                                 h["count"]))),
                Family("jax_mapping_jit_recompiles_total", "counter",
                       tuple((f'{{fn="{fn}"}}', str(n))
                             for fn, n in recs.items())),
            ]
            mem = self.devprof.memory_stats()
            if mem is not None:
                fams.append(Family(
                    "jax_mapping_device_memory_bytes", "gauge",
                    tuple((f'{{device="{d}",stat="{k}"}}', str(v))
                          for d, stats in mem.items()
                          for k, v in sorted(stats.items()))))
            return fams
        reg.add_source(devprof_families)

        def checkpoint_fallback_samples():
            # Which retention slot checkpoint loads actually resumed
            # from (ISSUE 12 satellite): a silent .prev / .genNNNNNN
            # rescue becomes a dashboard fact. All slots always report
            # (absent label != zero counter).
            from jax_mapping.io.checkpoint import fallback_counts
            return [(f'{{slot="{slot}"}}', str(n))
                    for slot, n in sorted(fallback_counts().items())]
        reg.family("jax_mapping_checkpoint_fallback_total", "counter",
                   checkpoint_fallback_samples)

        def tenancy_families():
            # Mission multi-tenancy (tenancy/): active/suspended/
            # evicted tenant counts, bucket capacity/occupancy and the
            # pad-slot waste fraction — ONE consistent control-plane
            # status snapshot per render. Whole block omitted when no
            # control plane is attached.
            cp = self.tenancy
            if cp is None:
                return None
            return cp.metric_families()
        reg.add_source(tenancy_families)

        def pipeline_families():
            # Freshness pipeline (obs/pipeline.py): per-hop fixed
            # log-bucket latency histograms (ONE family sliced by
            # hop/tenant labels — the devprof labeled-family idiom)
            # plus the end-to-end scan→served family. Host-mapper
            # series carry no tenant label; tenant namespaces slice
            # with `tenant="<id>"` (the PR 14 serving namespaces).
            if self.pipeline is None:
                return None
            from jax_mapping.obs.registry import (
                labeled_histogram_samples)
            hists = self.pipeline.histograms()
            hop_samples = []
            e2e_samples = []
            for (hop, tenant), h in sorted(hists.items()):
                if hop == "scan_to_served":
                    labels = f'tenant="{tenant}"' if tenant else None
                    if labels is None:
                        e2e_samples += histogram_samples(
                            h["edges_s"], h["buckets"], h["sum_s"],
                            h["count"])
                    else:
                        e2e_samples += labeled_histogram_samples(
                            labels, h["edges_s"], h["buckets"],
                            h["sum_s"], h["count"])
                    continue
                labels = f'hop="{hop}"' + \
                    (f',tenant="{tenant}"' if tenant else "")
                hop_samples += labeled_histogram_samples(
                    labels, h["edges_s"], h["buckets"], h["sum_s"],
                    h["count"])
            st = self.pipeline.status()
            fams = [
                Family("jax_mapping_pipeline_hop_seconds", "histogram",
                       tuple(hop_samples)),
                Family("jax_mapping_scan_to_served_seconds",
                       "histogram", tuple(e2e_samples)),
                Family("jax_mapping_pipeline_revisions_completed"
                       "_total", "counter",
                       (("", str(st["completed_revisions"])),)),
                Family("jax_mapping_pipeline_revisions_pending",
                       "gauge",
                       (("", str(st["pending_revisions"])),)),
                Family("jax_mapping_pipeline_revisions_evicted_total",
                       "counter",
                       (("", str(st["evicted_revisions"])),)),
            ]
            return fams
        reg.add_source(pipeline_families)

        def slo_families():
            # Freshness SLO engine (obs/slo.py): firing state, burn
            # rates and alert counters per objective — ONE consistent
            # engine snapshot per render (the tenancy pattern).
            if self.slo is None:
                return None
            return self.slo.metric_families()
        reg.add_source(slo_families)
        return reg

    # -- lifecycle ----------------------------------------------------------

    def serve_thread(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="http-api")
        self._thread.start()
        return self._thread

    def shutdown(self) -> None:
        # Wake every SSE/long-poll wait first: their worker threads are
        # daemons, but the bounded loops should exit promptly rather
        # than ride out their deadlines against a closing socket.
        self._shutting_down.set()
        if self.serving is not None:
            self.serving.events.close_all()
        # server.shutdown() blocks until the serve_forever loop acknowledges
        # — calling it when the loop never started would hang forever.
        if self._thread is not None:
            self.server.shutdown()
        self.server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
