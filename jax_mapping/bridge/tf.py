"""TF tree: timestamped transform buffer with interpolation.

Provides the capability of tf2_ros as used by the reference (SURVEY.md §1
L1): static transforms (base_link->base_laser, z=0.12 m,
`/root/reference/pi/src/thymio_project/launch/pi_hardware.launch.py:26-30`),
dynamic transforms (odom->base_link from the brain, map->odom from SLAM),
and chained lookups across the tree map->odom->base_link->base_laser.

The reference future-dated its odom TF by +0.1 s to beat slam_toolbox's
transform_timeout (`server/.../main.py:205`, SURVEY.md Appendix B). Here
stamps are honest and `lookup` interpolates between buffered samples —
extrapolating (clamped) beyond the newest, which is the principled version
of the same fix.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Tuple

from jax_mapping.bridge.messages import Header, TransformStamped


def _interp_angle(a: float, b: float, t: float) -> float:
    d = (b - a + math.pi) % (2 * math.pi) - math.pi
    return a + d * t


class _FrameBuffer:
    """Time-ordered samples of one parent->child transform."""

    def __init__(self, cache_time_s: float = 10.0):
        self.cache_time_s = cache_time_s
        self.stamps: List[float] = []
        self.tfs: List[TransformStamped] = []

    def insert(self, tf: TransformStamped) -> None:
        i = bisect.bisect(self.stamps, tf.header.stamp)
        self.stamps.insert(i, tf.header.stamp)
        self.tfs.insert(i, tf)
        cutoff = self.stamps[-1] - self.cache_time_s
        while len(self.stamps) > 1 and self.stamps[0] < cutoff:
            self.stamps.pop(0)
            self.tfs.pop(0)

    def sample(self, stamp: Optional[float]) -> TransformStamped:
        if stamp is None or len(self.stamps) == 1:
            return self.tfs[-1]
        if stamp >= self.stamps[-1]:
            return self.tfs[-1]          # clamp: no future extrapolation
        if stamp <= self.stamps[0]:
            return self.tfs[0]
        i = bisect.bisect(self.stamps, stamp)
        a, b = self.tfs[i - 1], self.tfs[i]
        t = (stamp - self.stamps[i - 1]) / max(
            self.stamps[i] - self.stamps[i - 1], 1e-9)
        return TransformStamped(
            header=Header(stamp=stamp, frame_id=a.header.frame_id),
            child_frame_id=a.child_frame_id,
            x=a.x + (b.x - a.x) * t,
            y=a.y + (b.y - a.y) * t,
            z=a.z + (b.z - a.z) * t,
            theta=_interp_angle(a.theta, b.theta, t),
        )


class TfTree:
    """Thread-safe transform buffer + graph search over frames."""

    def __init__(self, cache_time_s: float = 10.0):
        self.cache_time_s = cache_time_s
        self._lock = threading.Lock()
        # keyed by (parent, child)
        self._buffers: Dict[Tuple[str, str], _FrameBuffer] = {}
        self._static: Dict[Tuple[str, str], TransformStamped] = {}

    def set_transform(self, tf: TransformStamped) -> None:
        key = (tf.header.frame_id, tf.child_frame_id)
        with self._lock:
            buf = self._buffers.get(key)
            if buf is None:
                buf = self._buffers[key] = _FrameBuffer(self.cache_time_s)
            buf.insert(tf)

    def set_static_transform(self, tf: TransformStamped) -> None:
        with self._lock:
            self._static[(tf.header.frame_id, tf.child_frame_id)] = tf

    def all_transforms(self) -> List[TransformStamped]:
        """Latest sample of every edge (dynamic + static) — what a ROS TF
        broadcaster re-publishes (bridge/rclpy_adapter.py)."""
        out: List[TransformStamped] = []
        with self._lock:
            for buf in self._buffers.values():
                tf = buf.sample(None)
                if tf is not None:
                    out.append(tf)
            out.extend(self._static.values())
        return out

    # -- lookup -------------------------------------------------------------

    def _edges(self) -> Dict[str, List[Tuple[str, Tuple[str, str], bool]]]:
        """Adjacency: frame -> [(neighbor, edge_key, forward)]."""
        adj: Dict[str, List[Tuple[str, Tuple[str, str], bool]]] = {}
        for (p, c) in list(self._buffers.keys()) + list(self._static.keys()):
            adj.setdefault(p, []).append((c, (p, c), True))
            adj.setdefault(c, []).append((p, (p, c), False))
        return adj

    def _edge_tf(self, key: Tuple[str, str],
                 stamp: Optional[float]) -> TransformStamped:
        st = self._static.get(key)
        if st is not None:
            return st
        return self._buffers[key].sample(stamp)

    def lookup(self, target: str, source: str,
               stamp: Optional[float] = None) -> TransformStamped:
        """Transform that expresses `source`-frame points in `target` frame,
        chaining across the tree (e.g. map->base_laser through odom and
        base_link, the chain slam_toolbox resolves per SURVEY.md §3.3).

        Raises LookupError when the frames are not connected.
        """
        if target == source:
            return TransformStamped(header=Header(stamp=stamp or 0.0,
                                                  frame_id=target),
                                    child_frame_id=source)
        with self._lock:
            adj = self._edges()
            if target not in adj or source not in adj:
                raise LookupError(
                    f"tf: no path {target} -> {source} (unknown frame)")
            # BFS from target to source.
            prev: Dict[str, Tuple[str, Tuple[str, str], bool]] = {}
            frontier = [target]
            seen = {target}
            while frontier and source not in prev:
                nxt = []
                for f in frontier:
                    for (nb, key, fwd) in adj.get(f, ()):
                        if nb in seen:
                            continue
                        seen.add(nb)
                        prev[nb] = (f, key, fwd)
                        nxt.append(nb)
                frontier = nxt
            if source not in prev:
                raise LookupError(f"tf: no path {target} -> {source}")
            # Walk back source -> target collecting edges, then compose
            # target-side first.
            chain: List[Tuple[Tuple[str, str], bool]] = []
            node = source
            while node != target:
                parent, key, fwd = prev[node]
                chain.append((key, fwd))
                node = parent
            chain.reverse()
            out = TransformStamped(header=Header(stamp=stamp or 0.0,
                                                 frame_id=target),
                                   child_frame_id=source)
            for key, fwd in chain:
                tf = self._edge_tf(key, stamp)
                out = out.compose(tf if fwd else tf.inverse())
            out.child_frame_id = source
            out.header.frame_id = target
            return out

    def can_transform(self, target: str, source: str,
                      stamp: Optional[float] = None) -> bool:
        try:
            self.lookup(target, source, stamp)
            return True
        except LookupError:
            return False
