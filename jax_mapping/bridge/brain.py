"""ThymioBrain node: the reference's central control node, fleet-batched.

Re-creates `class ThymioBrain` (`/root/reference/server/thymio_project/
thymio_project/main.py:38-224`) against the bridge bus and the driver
abstraction, with the whole per-tick computation — odometry integration,
subsumption navigation, LED protocol — fused into ONE jitted JAX function
batched over robots (`brain_tick`), instead of the reference's scalar Python.

Kept behaviors (SURVEY.md §3.2, §5):
* connect on boot, offline mode on failure (pi variant, `pi/src/.../main.py:66-67`),
* throttled reconnect probe every ~2 s while disconnected — by wall clock,
  not the reference's nanosecond-modulo hack (`server/.../main.py:84-88`),
* any I/O exception ⇒ drop the link, reconnect next tick (`:198-200`),
* 16-bit sign fix on motor speed reads (`:101-102`),
* TF odom->base_link + `/odom` publication each tick (`:202-224`) with
  honest stamps (Appendix B),
* `is_exploring` start/stop contract (`:227-239`),
* LED status protocol green/blue/red/orange (`:131,161,181,192`).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import (
    LEDS_TOP, MOTOR_LEFT_SPEED, MOTOR_LEFT_TARGET, MOTOR_RIGHT_SPEED,
    MOTOR_RIGHT_TARGET, PROX_HORIZONTAL, connect_with_retries,
)
from jax_mapping.bridge.messages import (
    Header, LaserScan, Odometry, Pose2D, TransformStamped, Twist,
)
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.qos import qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig, sign_extend_16bit
from jax_mapping.utils import global_metrics as M
from jax_mapping.models.explorer import frontier_policy
from jax_mapping.ops.odometry import rk2_step, wheel_velocities
from jax_mapping.resilience.health import (
    DRIVER_OFFLINE, DRIVER_OK, DRIVER_RECOVERING, FleetHealth,
    acquire_bounded,
)
from jax_mapping.resilience.supervisor import Heartbeater


def robot_ns(i: int, n_robots: int) -> str:
    """Topic/frame namespace: '' for a single robot (reference layout),
    'robot<i>/' for fleets."""
    return "" if n_robots == 1 else f"robot{i}/"


@functools.partial(jax.jit, static_argnums=(0,))
def brain_tick(cfg: SlamConfig, poses, wheel_raw, prox, ranges,
               exploring, goals_xy, goal_valid, dt):
    """One fused control tick for R robots.

    poses (R,3) float32; wheel_raw (R,2) int32 raw unsigned16 reads;
    prox (R,>=5) int32; ranges (R,B) float32 (zeros when no scan yet);
    exploring (R,) bool; goals_xy (R,2) float32 + goal_valid (R,) bool
    (RViz SetGoal navigation targets; without a valid goal the policy is
    exactly the reference's reactive navigator); dt () float32.
    Returns (new_poses, odom_twists (R,2)[v,w], targets (R,2) int32,
    leds (R,3) int32, nav_state (R,) int32).
    """
    wheels = sign_extend_16bit(wheel_raw).astype(jnp.float32)
    new_poses = jax.vmap(
        lambda p, w: rk2_step(cfg.robot, p, w[0], w[1], dt))(poses, wheels)
    v, w = wheel_velocities(cfg.robot, wheels[:, 0], wheels[:, 1])
    # frontier_policy with goal_valid=False IS the subsumption policy
    # (goal seek only engages in the cruise state with a valid goal).
    pol = frontier_policy(cfg.robot, cfg.scan, poses, goals_xy, goal_valid,
                          ranges, prox[:, :5].astype(jnp.float32),
                          exploring)
    return (new_poses, jnp.stack([v, w], -1), pol.targets, pol.led,
            pol.state)


class ThymioBrain(Node):
    """Fleet brain; for n_robots=1 its graph is exactly the reference's."""

    def __init__(self, cfg: SlamConfig, bus: Bus, driver,
                 tf: Optional[TfTree] = None, n_robots: int = 1,
                 connect_retries: int = 3, connect_timeout_s: float = 3.0,
                 reconnect_period_s: float = 2.0,
                 health: Optional[FleetHealth] = None,
                 recovery=None):
        super().__init__("thymio_brain", bus, tf)
        self.cfg = cfg
        self.driver = driver
        self.n_robots = n_robots
        self.connect_retries = connect_retries
        self.connect_timeout_s = connect_timeout_s
        self.reconnect_period_s = reconnect_period_s
        #: Shared degraded-mode registry (resilience/health.py): this
        #: node FEEDS it (scan arrivals, tick clock, driver link) and
        #: READS the coast mask. None = pre-resilience behavior.
        self._health = health
        #: Estimator guardrails (recovery/manager.py): this node runs
        #: the anti-stuck recovery ladder each tick and advances the
        #: frontier blacklist's control-tick clock. None = pre-guardrail
        #: behavior exactly.
        self._recovery = recovery

        self._state_lock = threading.Lock()
        self.poses = np.zeros((n_robots, 3), np.float32)
        self.is_exploring = False                   # /start /stop contract
        self.link_up = False
        self._last_reconnect_probe = -1e9
        self.n_ticks = 0
        self._tick_no = 0
        self.n_io_errors = 0
        self._latest_scans: List[Optional[LaserScan]] = [None] * n_robots
        self._last_cmd_vel: Optional[Twist] = None
        self._last_cmd_vel_t = -1e9
        self.cmd_vel_timeout_s = 0.5

        self.odom_pubs = []
        for i in range(n_robots):
            ns = robot_ns(i, n_robots)
            self.create_subscription(
                f"{ns}scan",
                functools.partial(self._scan_cb, i),
                qos_sensor_data)                    # Best-Effort, §V.A
            self.odom_pubs.append(self.create_publisher(f"{ns}odom"))
        # Manual teleop override (bridge/teleop.py). Applies to robot 0 —
        # one pad drives one robot, the rest keep their autonomous policy.
        self.create_subscription("/cmd_vel", self._cmd_vel_cb)
        # RViz SetGoal (via the rclpy adapter): navigation goals.
        # /goal_pose addresses robot 0 (the reference's single-robot
        # convention; it shipped the RViz tool but no consumer — Nav2
        # was future work, report.pdf VI.2); fleets also get per-robot
        # {ns}goal_pose topics so an operator can direct ANY robot.
        # Cleared per robot on arrival.
        self._nav_goals: list = [None] * n_robots
        self.goal_reached_dist_m = 0.15
        self.create_subscription("/goal_pose",
                                 functools.partial(self._goal_cb, 0))
        if n_robots > 1:
            # Single-robot stacks skip this: robot_ns(0, 1) is '', so
            # the loop would subscribe a bare 'goal_pose' topic that
            # differs from the canonical '/goal_pose' every publisher
            # uses — a dead subscription that never fires.
            for i in range(n_robots):
                self.create_subscription(
                    f"{robot_ns(i, n_robots)}goal_pose",
                    functools.partial(self._goal_cb, i))
        # Planner waypoint (bridge/planner.py): while fresh, reachable,
        # and computed FOR the current goal, the brain steers at this
        # instead of the raw goal — map-aware navigation around walls.
        # Stale/absent waypoint (planner not launched, goal unreachable)
        # keeps the round-4 straight-line seek under the shield.
        self._waypoints: dict = {}
        self.create_subscription("/goal_waypoint", self._waypoint_cb)
        # Assigned-frontier exploration (FrontierConfig.seek_assigned):
        # the mapper's /frontiers assignments become goal-seek targets
        # for exploring robots without a manual nav goal — the map-based
        # explorer the reference's report defers to future work
        # (report.pdf VI.2), driving the actual robots instead of only
        # the RViz markers.
        self._frontiers = None
        self.create_subscription("/frontiers", self._frontiers_cb)
        # Per-robot planned waypoints toward assignments
        # (bridge/planner.py frontier waypoints): preferred over the raw
        # target when fresh, reachable, and planned for the SAME target.
        self._frontier_wps: dict = {}
        #: Per-stream high-water header stamps for the goal-state
        #: caches (_fresher): reorder protection that survives the TTL
        #: prune deleting the entries themselves.
        self._goal_stamp_hwm: dict = {}
        self.create_subscription("/frontier_waypoints",
                                 self._frontier_wp_cb)

        # Heartbeat for the Supervisor (beats EVERY update_loop call,
        # link up or not — the node is alive even when the robot link
        # is not).
        self._heartbeater = Heartbeater(self)
        # Safe-stop pending after a reconnect: the first post-reconnect
        # tick zeroes the motors and shows LED red instead of running
        # the policy, so stale pre-fault wheel targets never replay.
        self._safe_stop_pending = False

        # Boot connect, offline mode on failure (pi variant semantics).
        self.link_up = connect_with_retries(
            driver, max_retries=connect_retries,
            timeout_s=connect_timeout_s, log=self._log)
        self.timer = self.create_timer(1.0 / cfg.robot.control_rate_hz,
                                       self.update_loop)

    def _log(self, msg: str) -> None:
        print(f"[thymio_brain] {msg}")

    # -- callbacks ----------------------------------------------------------

    def _scan_cb(self, robot_idx: int, msg: LaserScan) -> None:
        with self._state_lock:
            self._latest_scans[robot_idx] = msg
        if self._health is not None:
            # Outside the state lock: FleetHealth is a leaf lock and
            # must never nest inside a node lock (B1 discipline).
            self._health.note_scan(robot_idx, self.n_ticks)

    def _cmd_vel_cb(self, msg: Twist) -> None:
        with self._state_lock:
            self._last_cmd_vel = msg
            self._last_cmd_vel_t = time.monotonic()

    def _goal_cb(self, i: int, msg) -> None:
        """Any pose-shaped message with .x/.y (the adapter's Pose2D)."""
        x, y = float(msg.x), float(msg.y)
        if not self.cfg.grid.contains_m(x, y):
            # The single goal ingress rejects non-finite and off-map
            # coordinates (GridConfig.contains_m — the same predicate
            # the planner and HTTP ingresses gate on): a NaN goal can
            # never be reached or cleared and would feed NaN through
            # brain_tick into that robot's wheel targets until restart;
            # a goal outside the map would clip to a border cell and
            # drive the robot toward a place that does not exist.
            self._log(f"ignoring non-finite or out-of-map goal for "
                      f"robot {i}: ({x}, {y})")
            return
        with self._state_lock:
            self._nav_goals[i] = (x, y)
        self._log(f"navigation goal set for robot {i}: "
                  f"({x:.2f}, {y:.2f}) — engages while exploring")

    def _fresher(self, key, msg) -> bool:
        """Goal-state reorder watermark: under Best-Effort delivery (and
        the chaos bus's reorder weather) a STALE /frontiers or waypoint
        message can arrive after a fresher one — accepting it would
        resurrect an assignment the mapper has since dropped and send a
        robot seeking a goal that no longer exists. The high-water
        stamps live in their OWN map (`_goal_stamp_hwm`), deliberately
        NOT in the cached entries: the TTL prune deletes entries, and a
        watermark that died with its entry would wave through a stale
        message flushed after a TTL-length gap (a healed reorder window
        draining its backlog past a dead mapper). Caller holds the
        state lock."""
        hwm = self._goal_stamp_hwm.get(key)
        if hwm is not None and msg.header.stamp < hwm:
            return False
        self._goal_stamp_hwm[key] = msg.header.stamp
        return True

    def _waypoint_cb(self, msg) -> None:
        with self._state_lock:
            r = int(getattr(msg, "robot", 0))
            if self._fresher(("wp", r), msg):
                self._waypoints[r] = (msg, self.n_ticks)

    def _frontiers_cb(self, msg) -> None:
        with self._state_lock:
            if self._fresher("frontiers", msg):
                self._frontiers = (msg, self.n_ticks)

    def _frontier_wp_cb(self, msg) -> None:
        with self._state_lock:
            r = int(getattr(msg, "robot", 0))
            if self._fresher(("fwp", r), msg):
                self._frontier_wps[r] = (msg, self.n_ticks)

    def _prune_stale_goal_state(self) -> None:
        """Expire frontier-goal state past its TTL (once per tick).

        The TTL gates at the READ sites already keep stale entries from
        steering; this prune makes expiry STRUCTURAL — the entries are
        deleted, so no future read path can forget the gate, a dead
        mapper's last assignment cannot linger in memory for the rest of
        the mission, and the waypoint dicts stay bounded."""
        rate = self.cfg.robot.control_rate_hz
        ttl_wp = self.cfg.planner.waypoint_ttl_s * rate
        ttl_fr = self.cfg.frontier.seek_ttl_s * rate
        with self._state_lock:
            self._waypoints = {
                r: (m, t) for r, (m, t) in self._waypoints.items()
                if self.n_ticks - t <= ttl_wp}
            self._frontier_wps = {
                r: (m, t) for r, (m, t) in self._frontier_wps.items()
                if self.n_ticks - t <= ttl_wp}
            if self._frontiers is not None \
                    and self.n_ticks - self._frontiers[1] > ttl_fr:
                self._frontiers = None

    def _apply_frontier_goals(self, goals_xy: np.ndarray,
                              goal_valid: np.ndarray) -> None:
        """Fill unset goal rows from the freshest /frontiers assignment.

        Manual nav goals (already-valid rows) win; robots whose
        assignment is -1 (no reachable frontier) keep the blind cruise
        fallback. Staleness is measured in control ticks like the
        planner waypoint, and for the same reason."""
        if not self.cfg.frontier.seek_assigned:
            return
        with self._state_lock:
            entry = self._frontiers
            fwps = dict(self._frontier_wps)
        if entry is None:
            return
        msg, at_tick = entry
        ttl_ticks = (self.cfg.frontier.seek_ttl_s
                     * self.cfg.robot.control_rate_hz)
        if self.n_ticks - at_tick > ttl_ticks:
            return
        targets = np.asarray(msg.targets_xy, np.float32)
        assign = np.asarray(msg.assignment)
        ttl_wp = (self.cfg.planner.waypoint_ttl_s
                  * self.cfg.robot.control_rate_hz)
        # A planned waypoint must have been computed for (about) THIS
        # target — clusters drift between publishes, so the echo match
        # is per-coarse-cell, not exact.
        tol = (self.cfg.grid.resolution_m * self.cfg.frontier.downsample
               * 2.0)
        for i in range(min(self.n_robots, len(assign))):
            a = int(assign[i])
            if goal_valid[i] or not 0 <= a < len(targets):
                continue
            goals_xy[i] = targets[a]
            goal_valid[i] = True
            wp_entry = fwps.get(i)
            if wp_entry is None:
                continue
            wp, wp_tick = wp_entry
            if (wp.reachable and self.n_ticks - wp_tick <= ttl_wp
                    and np.hypot(wp.goal_x - targets[a][0],
                                 wp.goal_y - targets[a][1]) <= tol):
                goals_xy[i] = (wp.x, wp.y)

    def _blacklist_current_goal(self, i: int) -> None:
        """Anti-stuck rung 3 — goal reassignment: robot i has proven its
        current goal unreachable-in-practice (two maneuver rungs did not
        free it). A manual nav goal is CANCELLED (the escape hatch — the
        operator's goal is the thing the robot cannot reach); a frontier
        assignment is blacklisted with TTL so the auction's post-pass
        (mapper._apply_blacklist) hands robot i a different frontier."""
        with self._state_lock:
            manual = self._nav_goals[i]
            entry = self._frontiers
        if manual is not None:
            # Cancelled, NOT blacklisted: the operator deliberately
            # pointed at this area — barring every frontier within the
            # blacklist tolerance of it would suppress exploration
            # around the very point they care about. Cancelling reverts
            # the robot to frontier exploration, which approaches the
            # region by other routes.
            self.cancel_goal(i)
            self._log(f"anti-stuck: unreachable manual goal cancelled "
                      f"(robot {i})")
            return
        if entry is None:
            return
        msg, _ = entry
        assign = np.asarray(msg.assignment)
        if i >= len(assign):
            return
        a = int(assign[i])
        targets = np.asarray(msg.targets_xy, np.float32)
        if 0 <= a < len(targets):
            self._recovery.blacklist.add(
                i, (float(targets[a][0]), float(targets[a][1])))
            self._log(f"anti-stuck: frontier ({targets[a][0]:.2f}, "
                      f"{targets[a][1]:.2f}) blacklisted for robot {i}")

    def nav_goal(self) -> Optional[tuple]:
        """Robot 0's navigation goal (planner reads the brain's copy so
        a reached-and-cleared goal stops replanning)."""
        with self._state_lock:
            return self._nav_goals[0]

    def nav_goals(self) -> list:
        """Every robot's manual goal (None where unset)."""
        with self._state_lock:
            return list(self._nav_goals)

    def cancel_goal(self, i: int) -> bool:
        """Clear robot i's manual goal; returns whether one was set.
        The robot reverts to frontier exploration (or cruise) — the
        escape hatch for an unreachable goal the operator regrets."""
        with self._state_lock:
            had = self._nav_goals[i] is not None
            self._nav_goals[i] = None
        if had:
            self._log(f"navigation goal cancelled (robot {i})")
        return had

    def robot_pose(self, i: int) -> np.ndarray:
        with self._state_lock:
            return self.poses[i].copy()

    def _steer_target(self, goal: tuple, robot: int = 0) -> tuple:
        """The point `robot` steers at for `goal`: the planner's lookahead
        waypoint when fresh + reachable + computed for THIS goal, else the
        goal itself. Freshness is measured in CONTROL TICKS, not wall
        time: faster-than-realtime stacks (Stack.run_steps, demo) replan
        every period_s of simulated control time, and a wall-clock TTL
        would silently expire every waypoint on a host where a replan
        window of sim steps takes longer than the TTL to execute —
        host-speed-dependent trajectories in the deterministic path."""
        with self._state_lock:
            entry = self._waypoints.get(robot)
        if entry is None:
            return goal
        wp, at_tick = entry
        if not wp.reachable:
            return goal
        ttl_ticks = (self.cfg.planner.waypoint_ttl_s
                     * self.cfg.robot.control_rate_hz)
        if self.n_ticks - at_tick > ttl_ticks:
            return goal
        if np.hypot(wp.goal_x - goal[0], wp.goal_y - goal[1]) > 1e-3:
            return goal                      # plan for a superseded goal
        return (wp.x, wp.y)

    def _manual_targets(self, now: float):
        """Fresh `/cmd_vel` while not exploring -> (left, right) wheel
        units for robot 0, else None. Inverse of the odometry kinematics
        (`server/.../main.py:105-115`): v = K*(l+r)/2, w = K*(r-l)/width."""
        with self._state_lock:
            cmd = self._last_cmd_vel
            fresh = now - self._last_cmd_vel_t <= self.cmd_vel_timeout_s
            exploring = self.is_exploring
        if exploring or cmd is None or not fresh:
            return None
        r = self.cfg.robot
        k = r.speed_coeff_m_per_unit_s
        half_w = r.wheel_base_m / 2.0
        left = (cmd.linear_x - cmd.angular_z * half_w) / k
        right = (cmd.linear_x + cmd.angular_z * half_w) / k
        lim = float(r.motor_limit_units)              # Thymio target range
        return (int(np.clip(left, -lim, lim)), int(np.clip(right, -lim, lim)))

    def start_exploring(self) -> None:
        with self._state_lock:
            self.is_exploring = True

    def stop_exploring(self) -> None:
        """Stop AND force motors off immediately — the pi variant's safe
        stop (`pi/src/.../main.py:320-326`), not just a flag flip."""
        with self._state_lock:
            self.is_exploring = False
        if self.link_up:
            try:
                for i in range(self.n_robots):
                    self.driver[i][MOTOR_LEFT_TARGET] = 0
                    self.driver[i][MOTOR_RIGHT_TARGET] = 0
            except Exception:                       # noqa: BLE001
                self._drop_link()

    def status(self, lock_timeout_s: Optional[float] = None) -> dict:
        """The pi variant's `/status` payload (`pi/src/.../main.py:332-341`).

        `lock_timeout_s` bounds the state-lock wait (the HTTP plane
        passes ResilienceConfig.http_lock_timeout_s); expiry raises
        LockTimeout, which the API layer answers as 503 degraded instead
        of hanging a worker thread behind a wedged tick."""
        acquire_bounded(self._state_lock, lock_timeout_s,
                        "thymio_brain state")
        try:
            return {
                "connected": self.link_up,
                "exploring": self.is_exploring,
                "n_robots": self.n_robots,
                "poses": [
                    {"x": float(p[0]), "y": float(p[1]),
                     "theta": float(p[2])} for p in self.poses],
                "ticks": self.n_ticks,
                "io_errors": self.n_io_errors,
                "goal": (None if self._nav_goals[0] is None
                         else {"x": self._nav_goals[0][0],
                               "y": self._nav_goals[0][1]}),
                "goals": [
                    (None if g is None else {"x": g[0], "y": g[1]})
                    for g in self._nav_goals],
            }
        finally:
            self._state_lock.release()

    # -- the 10 Hz loop ------------------------------------------------------

    def _drop_link(self) -> None:
        self.n_io_errors += 1
        self.link_up = False
        if self._health is not None:
            self._health.note_driver(DRIVER_OFFLINE)
        try:
            self.driver.disconnect()
        except Exception:                           # noqa: BLE001
            pass

    def _ranges_matrix(self) -> np.ndarray:
        """Latest scans resampled to (R, n_beams); zeros (= no reading,
        which the policy's outlier rule reads as far) when absent."""
        B = self.cfg.scan.n_beams
        out = np.zeros((self.n_robots, B), np.float32)
        with self._state_lock:
            scans = list(self._latest_scans)
        for i, scan in enumerate(scans):
            if scan is None or len(scan.ranges) == 0:
                continue
            r = np.asarray(scan.ranges, np.float32)
            if len(r) == B:
                out[i] = r
            else:
                idx = np.linspace(0, len(r) - 1, B).round().astype(int)
                out[i] = r[idx]
        return out

    def _beat(self) -> None:
        """One heartbeat per update_loop call — the node is alive even
        when the robot link is not (payload says which)."""
        self._heartbeater.beat(
            {"link_up": self.link_up, "ticks": self.n_ticks,
             "io_errors": self.n_io_errors})

    def _safe_stop_all(self) -> None:
        """Zero every robot's motors + LED red: the post-reconnect (and
        degraded-entry) posture. Raises on I/O error like any driver
        write — callers handle via the usual drop-link path."""
        for i in range(self.n_robots):
            self.driver[i][MOTOR_LEFT_TARGET] = 0
            self.driver[i][MOTOR_RIGHT_TARGET] = 0
            self.driver[i][LEDS_TOP] = [32, 0, 0]       # red: degraded

    def update_loop(self) -> None:
        # Causal tracing (obs/): one `brain.tick` span per control tick
        # when armed, so motor/odometry publishes made here chain under
        # the tick that commanded them; a stage timer either way (the
        # control loop's latency histogram on /metrics).
        self._tick_no += 1
        tracer = getattr(self.bus, "tracer", None)
        with M.stages.stage("brain.tick"):
            if tracer is not None:
                with tracer.span("brain.tick", key=self._tick_no):
                    self._update_loop_body()
            else:
                self._update_loop_body()

    def _update_loop_body(self) -> None:
        cfg = self.cfg
        now = time.monotonic()
        if self._health is not None:
            self._health.note_tick(self.n_ticks)
        # Structural expiry of stale frontier-goal state runs regardless
        # of recovery: the read-site TTL gates already ignore these
        # entries, deletion just makes that un-forgettable (and bounds
        # the dicts) — behavior under the gates is unchanged.
        self._prune_stale_goal_state()
        if self._recovery is not None:
            # One clock for every recovery TTL (blacklist expiry).
            self._recovery.blacklist.note_tick(self.n_ticks)
        if not self.link_up:
            if self._health is not None:
                self._health.note_driver(DRIVER_OFFLINE)
            # Throttled reconnect probe (`server/.../main.py:84-88`).
            if now - self._last_reconnect_probe < self.reconnect_period_s:
                self._beat()
                return
            self._last_reconnect_probe = now
            self.link_up = connect_with_retries(
                self.driver, max_retries=1,
                timeout_s=self.connect_timeout_s, log=self._log)
            if not self.link_up:
                self._beat()
                return
            # Reconnected: next tick runs the safe-stop BEFORE any
            # policy output reaches the motors.
            self._safe_stop_pending = True

        if self._safe_stop_pending:
            # One recovery tick: motors zeroed, LED red — the stale
            # targets a pre-fault tick wrote must not keep driving the
            # robot, and no policy targets are computed from the stale
            # sensor snapshot either (no duplicate motor commands).
            try:
                self._safe_stop_all()
                self._safe_stop_pending = False
                if self._health is not None:
                    self._health.note_driver(DRIVER_RECOVERING)
            except Exception:                   # noqa: BLE001
                self._drop_link()
            self._beat()
            return

        try:
            R = self.n_robots
            wheel_raw = np.zeros((R, 2), np.int32)
            prox = np.zeros((R, 7), np.int32)
            for i in range(R):
                wheel_raw[i, 0] = self.driver[i][MOTOR_LEFT_SPEED]
                wheel_raw[i, 1] = self.driver[i][MOTOR_RIGHT_SPEED]
                prox[i] = self.driver[i][PROX_HORIZONTAL]

            with self._state_lock:
                poses = self.poses.copy()
                exploring = np.full(R, self.is_exploring)
                goals = list(self._nav_goals)
            coast = np.zeros(R, bool)
            if self._health is not None:
                # Degraded mode: a robot whose lidar went silent COASTS —
                # no commanded motion (exploring off ⇒ the policy zeros
                # its targets), odometry keeps integrating so the pose
                # estimate survives for the rejoin. DEAD robots coast
                # too; the fleet has already reassigned their frontiers.
                lidar_ok = self._health.lidar_ok_mask()
                coast = ~lidar_ok
                exploring = exploring & lidar_ok
            ranges = self._ranges_matrix()
            goals_xy = np.zeros((R, 2), np.float32)
            goal_valid = np.zeros(R, bool)
            for i, goal in enumerate(goals):
                if goal is None:
                    continue
                if np.hypot(poses[i, 0] - goal[0],
                            poses[i, 1] - goal[1]) \
                        <= self.goal_reached_dist_m:
                    with self._state_lock:
                        # Compare-and-clear: a goal published between
                        # this tick's snapshot and now must not be
                        # silently erased by arrival at the OLD goal.
                        if self._nav_goals[i] == goal:
                            self._nav_goals[i] = None
                    self._log(f"navigation goal reached (robot {i})")
                else:
                    goals_xy[i] = self._steer_target(goal, i)
                    goal_valid[i] = True
            self._apply_frontier_goals(goals_xy, goal_valid)

            new_poses, twists, targets, leds, _ = brain_tick(
                cfg, poses, wheel_raw, prox, ranges, exploring,
                goals_xy, goal_valid,
                np.float32(1.0 / cfg.robot.control_rate_hz))
            new_poses = np.asarray(new_poses)
            twists = np.asarray(twists)
            targets_np = np.array(targets)          # writable: teleop override
            leds_np = np.array(leds)

            manual = self._manual_targets(now)
            if self._recovery is not None:
                # Anti-stuck recovery ladder: detect commanded-but-
                # motionless robots, escalate rotate -> backup ->
                # blacklist. Detection skips coasting / idle / manual
                # robots; maneuver overrides yield to the IR emergency
                # pivot (the shield stays the last word on contact) and
                # to manual drive (the operator IS the safety system).
                active = exploring & ~coast
                if manual is not None:
                    active[0] = False
                overrides, blacklist_req = self._recovery.antistuck.step(
                    self.n_ticks, new_poses, targets_np, active)
                # The IR emergency from the HOST-side prox snapshot (the
                # same predicate the policy's state 2 computes on
                # device) — no extra device fetch in the hot path.
                ir_stop = prox[:, :5].max(axis=1) > cfg.robot.ir_threshold
                for r, tgt in overrides.items():
                    if not ir_stop[r]:              # IR pivot outranks
                        targets_np[r] = tgt
                        leds_np[r] = (0, 32, 32)    # cyan: recovery
                for r in blacklist_req:
                    self._blacklist_current_goal(r)
            if manual is not None:
                targets_np[0] = manual
                leds_np[0] = (32, 32, 32)   # white: manual drive (extension
                #                             to the reference's LED states)
            if coast.any():
                # Orange = degraded (the reference's warn color): lidar
                # silent, coasting. Outranks policy colors; manual drive
                # white still wins (the operator IS the safety system).
                coast_led = coast.copy()
                if manual is not None:
                    coast_led[0] = False
                leds_np[coast_led] = (32, 16, 0)
                if self._health is not None:
                    # Magenta = estimator diverged (quarantined, the
                    # mapper is relocalizing it) — distinguishable from
                    # the lidar-silent orange at a glance.
                    div = self._health.diverged_mask()[:R] & coast_led
                    leds_np[div] = (32, 0, 32)

            for i in range(R):
                self.driver[i][MOTOR_LEFT_TARGET] = int(targets_np[i, 0])
                self.driver[i][MOTOR_RIGHT_TARGET] = int(targets_np[i, 1])
                self.driver[i][LEDS_TOP] = leds_np[i].tolist()

            with self._state_lock:
                self.poses = new_poses
            self.publish_tf(new_poses, twists)
            self.n_ticks += 1
            if self._health is not None:
                self._health.note_driver(DRIVER_OK)
        except Exception:                           # noqa: BLE001
            # Reference catch-all: drop and re-probe (`main.py:198-200`).
            self._drop_link()
        self._beat()

    def publish_tf(self, poses: np.ndarray, twists: np.ndarray) -> None:
        """TF odom->base_link + `/odom`, honest stamps
        (`server/.../main.py:202-224`, Appendix B)."""
        stamp = time.monotonic()
        for i in range(self.n_robots):
            ns = robot_ns(i, self.n_robots)
            p = poses[i]
            self.tf.set_transform(TransformStamped(
                header=Header(stamp=stamp, frame_id=f"{ns}odom"),
                child_frame_id=f"{ns}base_link",
                x=float(p[0]), y=float(p[1]), theta=float(p[2])))
            self.odom_pubs[i].publish(Odometry(
                header=Header(stamp=stamp, frame_id=f"{ns}odom"),
                child_frame_id=f"{ns}base_link",
                pose=Pose2D.from_array(p),
                twist=Twist(linear_x=float(twists[i, 0]),
                            angular_z=float(twists[i, 1]))))
