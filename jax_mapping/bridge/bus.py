"""In-process pub/sub bus with DDS-like QoS semantics.

Plays the role CycloneDDS plays in the reference (SURVEY.md §1 LX): topics
scoped by domain id (`ROS_DOMAIN_ID=42`, `/root/reference/README.md:86`,
`pi/Dockerfile:3`), per-subscription bounded queues, Best-Effort vs Reliable
delivery, transient-local latching for late joiners (the `/map` pattern),
and optional fault injection (drop probability, reordering) so the scan
batcher's tolerance to lossy Wi-Fi delivery (report.pdf §V.A) is testable —
the race-condition coverage the reference never had (SURVEY.md §4, §5).

Unlike the reference's GIL-reliant unsynchronized sharing
(`server/.../main.py:285-287`), every queue here is explicitly locked.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from jax_mapping.bridge.qos import Durability, QoSProfile, Reliability, \
    qos_default


class Subscription:
    """A bounded mailbox attached to one topic.

    When the bus carries a Tracer (ObsConfig.enabled), a parallel
    context deque shadows the message queue in LOCKSTEP — every append,
    overflow-drop and pop mutates both under the one mailbox lock — so
    the causal `TraceContext` of each sample survives queueing and is
    re-established around callback delivery. With no tracer the shadow
    queue is never constructed: the pre-obs hot path, bit-exact.
    """

    def __init__(self, bus: "Bus", topic: str, qos: QoSProfile,
                 callback: Optional[Callable[[Any], None]] = None):
        self.bus = bus
        self.topic = topic
        self.qos = qos
        self.callback = callback
        self._queue: collections.deque = collections.deque(maxlen=None)
        #: Trace-context shadow queue (tracing only; None otherwise).
        self._ctxq: Optional[collections.deque] = \
            collections.deque(maxlen=None) if bus.tracer is not None \
            else None
        #: Context of the most recent take() — a convenience for
        #: single-threaded mailbox consumers (poll loop reads it right
        #: after its take). The bus's own delivery path does NOT read
        #: it: concurrent publishers to one topic each run the
        #: take-then-deliver sequence, so delivery carries the context
        #: through `_take_with_ctx`'s return value instead of this
        #: shared field.
        self.taken_ctx = None
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self.n_received = 0
        self.n_dropped = 0
        self._closed = False

    def _offer(self, msg: Any, ctx=None) -> None:
        """Called by the bus on publish. Best-Effort drops oldest on
        overflow; Reliable blocks the publisher until there is room."""
        with self._lock:
            if self._closed:
                return
            if len(self._queue) >= self.qos.depth:
                if self.qos.reliability is Reliability.BEST_EFFORT:
                    self._queue.popleft()
                    if self._ctxq is not None and self._ctxq:
                        self._ctxq.popleft()
                    self.n_dropped += 1
                else:
                    while len(self._queue) >= self.qos.depth \
                            and not self._closed:
                        if not self._not_full.wait(timeout=5.0):
                            # Deadlock breaker: a reliable reader that has
                            # stalled for 5 s forfeits its oldest sample.
                            self._queue.popleft()
                            if self._ctxq is not None and self._ctxq:
                                self._ctxq.popleft()
                            self.n_dropped += 1
                            break
            self._queue.append(msg)
            if self._ctxq is not None:
                self._ctxq.append(ctx)
            self.n_received += 1
            self._not_empty.notify()

    def take(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Pop the oldest pending sample, or None on timeout."""
        return self._take_with_ctx(timeout)[0]

    def _take_with_ctx(self, timeout: Optional[float] = None) -> Tuple:
        """take() that also returns the sample's TraceContext (None
        when tracing is off) — both popped under ONE lock hold, so the
        pairing survives concurrent takers (the bus delivery path's
        contract; `taken_ctx` alone would race)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._queue:
                if deadline is None or self._closed:
                    return None, None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None, None
                self._not_empty.wait(timeout=remaining)
            msg = self._queue.popleft()
            ctx = None
            if self._ctxq is not None:
                ctx = self._ctxq.popleft() if self._ctxq else None
                self.taken_ctx = ctx
            self._not_full.notify()
            return msg, ctx

    def take_all(self) -> List[Any]:
        """Drain everything pending — the batcher's bulk read."""
        with self._lock:
            msgs = list(self._queue)
            self._queue.clear()
            if self._ctxq is not None:
                self._ctxq.clear()
            self._not_full.notify_all()
            return msgs

    def latest(self) -> Optional[Any]:
        """Drop all but the newest sample and return it (the reference's
        `latest_scan`/`latest_map` caching pattern, `server/.../main.py:
        77-81`, made explicit)."""
        msgs = self.take_all()
        return msgs[-1] if msgs else None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()
        self.bus._remove_subscription(self)


class Publisher:
    def __init__(self, bus: "Bus", topic: str, qos: QoSProfile):
        self.bus = bus
        self.topic = topic
        self.qos = qos
        self.n_published = 0

    def publish(self, msg: Any) -> None:
        self.n_published += 1
        self.bus._dispatch(self.topic, msg, self.qos)


class Bus:
    """One DDS domain: topic registry + delivery + fault injection.

    `drop_prob`/`reorder_prob` act on Best-Effort subscriptions only
    (Reliable delivery must never lose data) — modelling lossy Wi-Fi between
    the Pi and the PC (report.pdf §V.A).
    """

    def __init__(self, domain_id: int = 42, drop_prob: float = 0.0,
                 reorder_prob: float = 0.0, seed: int = 0, tracer=None):
        self.domain_id = domain_id
        self.drop_prob = drop_prob
        self.reorder_prob = reorder_prob
        #: Causal tracing (obs/trace.Tracer) or None. Fixed at
        #: construction: every publish derives a deterministic
        #: TraceContext (root ids from (seed, topic, seq)) that rides
        #: the subscription mailboxes and wraps callback delivery.
        #: None = the pre-obs hot path, not a single extra branch taken
        #: per message (ObsConfig.enabled=False bit-exactness).
        self.tracer = tracer
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Subscription]] = {}
        self._latched: Dict[str, Any] = {}
        self._reorder_hold: Dict[Tuple[int, str], Any] = {}
        #: Topics whose link is down (FaultPlan windows): publishes are
        #: dropped entirely, Reliable included — a dead transport loses
        #: everything, unlike the probabilistic Best-Effort weather.
        self._partitioned: set = set()
        self.n_partition_dropped = 0
        #: Closed subscriptions' received/dropped totals folded in per
        #: topic, so the /metrics bus counters stay Prometheus-monotonic
        #: across subscriber churn (the EventChannel carry-over rule).
        self._retired_stats: Dict[str, Dict[str, int]] = {}

    # -- fault injection (resilience/faultplan.py boundaries) ---------------

    def set_fault_injection(self, drop_prob: Optional[float] = None,
                            reorder_prob: Optional[float] = None) -> None:
        """Adjust the Best-Effort loss weather mid-run (FaultPlan
        drop/reorder windows). None leaves a knob unchanged."""
        with self._lock:
            if drop_prob is not None:
                self.drop_prob = drop_prob
            if reorder_prob is not None:
                self.reorder_prob = reorder_prob

    def partition(self, *topics: str) -> None:
        """Take topic links down — every publish on them vanishes until
        `heal`. The scripted stand-in for a dead sensor transport or a
        network partition between nodes."""
        with self._lock:
            self._partitioned.update(topics)

    def heal(self, *topics: str) -> None:
        """Restore partitioned topics (all of them when none named)."""
        with self._lock:
            if topics:
                self._partitioned.difference_update(topics)
            else:
                self._partitioned.clear()

    def partitioned_topics(self) -> List[str]:
        with self._lock:
            return sorted(self._partitioned)

    # -- graph construction -------------------------------------------------

    def publisher(self, topic: str, qos: QoSProfile = qos_default
                  ) -> Publisher:
        return Publisher(self, topic, qos)

    def subscribe(self, topic: str, qos: QoSProfile = qos_default,
                  callback: Optional[Callable[[Any], None]] = None
                  ) -> Subscription:
        sub = Subscription(self, topic, qos, callback)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
            latched = self._latched.get(topic)
        if latched is not None \
                and qos.durability is Durability.TRANSIENT_LOCAL:
            sub._offer(latched)
            if sub.callback is not None:
                m, ctx = sub._take_with_ctx()
                if m is not None:
                    self._deliver(sub, m, ctx)
        return sub

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._subs.keys() | self._latched.keys())

    def subscription_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-TOPIC subscription health, aggregated over that topic's
        subscriptions: live queue depth, samples received, samples
        dropped (overflow + loss weather) — the drop counters that were
        recorded but invisible before the `/metrics` bus families.
        Each mailbox is sampled under its own lock (consistent per-sub;
        the cross-sub aggregate is a snapshot like every /status
        read)."""
        with self._lock:
            by_topic = {t: list(subs) for t, subs in self._subs.items()}
            retired = {t: dict(v) for t, v in self._retired_stats.items()}
        out: Dict[str, Dict[str, int]] = {}
        for topic in sorted(by_topic.keys() | retired.keys()):
            base = retired.get(topic, {})
            agg = {"subscriptions": 0, "queue_depth": 0,
                   "n_received": base.get("n_received", 0),
                   "n_dropped": base.get("n_dropped", 0)}
            for sub in by_topic.get(topic, ()):
                with sub._lock:
                    agg["subscriptions"] += 1
                    agg["queue_depth"] += len(sub._queue)
                    agg["n_received"] += sub.n_received
                    agg["n_dropped"] += sub.n_dropped
            out[topic] = agg
        return out

    # -- delivery -----------------------------------------------------------

    def _dispatch(self, topic: str, msg: Any, pub_qos: QoSProfile) -> None:
        # Causal tracing: derive this publish's context BEFORE delivery
        # (root ids deterministic from (seed, topic, seq); a publish
        # inside a traced callback chains as a child). The context is a
        # side-channel — the message object is never touched — and it
        # rides the reorder hold / mailbox queues next to its sample.
        ctx = self.tracer.on_publish(topic) if self.tracer is not None \
            else None
        # One lock acquisition covers the latch write and the subscriber
        # snapshot, so a subscriber joining mid-publish cannot receive the
        # sample twice (once from the latch, once from the snapshot).
        with self._lock:
            if topic in self._partitioned:
                # Link down (FaultPlan): nothing latches, nothing
                # delivers — a dead transport, not lossy weather.
                self.n_partition_dropped += 1
                return
            if pub_qos.durability is Durability.TRANSIENT_LOCAL:
                self._latched[topic] = msg
            subs = list(self._subs.get(topic, ()))
        for sub in subs:
            delivery = [(msg, ctx)]
            if sub.qos.reliability is Reliability.BEST_EFFORT:
                with self._lock:
                    if self._rng.random() < self.drop_prob:
                        sub.n_dropped += 1
                        continue
                    key = (id(sub), topic)
                    if self._rng.random() < self.reorder_prob:
                        # Hold this sample; release it after the next one.
                        held = self._reorder_hold.pop(key, None)
                        self._reorder_hold[key] = (msg, ctx)
                        if held is None:
                            continue
                        delivery = [held]
                    else:
                        held = self._reorder_hold.pop(key, None)
                        if held is not None:
                            # swapped order
                            delivery = [(msg, ctx), held]
            for m, c in delivery:
                sub._offer(m, c)
                if sub.callback is not None:
                    taken, taken_ctx = sub._take_with_ctx()
                    if taken is not None:
                        self._deliver(sub, taken, taken_ctx)

    def _deliver(self, sub: Subscription, msg: Any, ctx=None) -> None:
        """Invoke a subscription callback with the sample's causal
        context current (thread-local) for the duration — how a
        subscriber's own publishes and captured contexts (e.g. the
        mapper's per-scan context) chain back to the publish that
        caused them. `ctx` is the context popped WITH the sample
        (`_take_with_ctx`), never the shared `taken_ctx` field —
        concurrent publishers to one topic would race that field
        between take and delivery and misattribute causal chains."""
        if self.tracer is not None and ctx is not None:
            with self.tracer.use(ctx):
                sub.callback(msg)
        else:
            sub.callback(msg)

    def _remove_subscription(self, sub: Subscription) -> None:
        with self._lock:
            lst = self._subs.get(sub.topic)
            if lst and sub in lst:
                lst.remove(sub)
                # Fold the departing mailbox's totals into the retired
                # carry (monotone /metrics counters across churn). The
                # sub is closed: its counters are final.
                agg = self._retired_stats.setdefault(
                    sub.topic, {"n_received": 0, "n_dropped": 0})
                agg["n_received"] += sub.n_received
                agg["n_dropped"] += sub.n_dropped
