"""In-process pub/sub bus with DDS-like QoS semantics.

Plays the role CycloneDDS plays in the reference (SURVEY.md §1 LX): topics
scoped by domain id (`ROS_DOMAIN_ID=42`, `/root/reference/README.md:86`,
`pi/Dockerfile:3`), per-subscription bounded queues, Best-Effort vs Reliable
delivery, transient-local latching for late joiners (the `/map` pattern),
and optional fault injection (drop probability, reordering) so the scan
batcher's tolerance to lossy Wi-Fi delivery (report.pdf §V.A) is testable —
the race-condition coverage the reference never had (SURVEY.md §4, §5).

Unlike the reference's GIL-reliant unsynchronized sharing
(`server/.../main.py:285-287`), every queue here is explicitly locked.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from jax_mapping.bridge.qos import Durability, QoSProfile, Reliability, \
    qos_default


class Subscription:
    """A bounded mailbox attached to one topic."""

    def __init__(self, bus: "Bus", topic: str, qos: QoSProfile,
                 callback: Optional[Callable[[Any], None]] = None):
        self.bus = bus
        self.topic = topic
        self.qos = qos
        self.callback = callback
        self._queue: collections.deque = collections.deque(maxlen=None)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self.n_received = 0
        self.n_dropped = 0
        self._closed = False

    def _offer(self, msg: Any) -> None:
        """Called by the bus on publish. Best-Effort drops oldest on
        overflow; Reliable blocks the publisher until there is room."""
        with self._lock:
            if self._closed:
                return
            if len(self._queue) >= self.qos.depth:
                if self.qos.reliability is Reliability.BEST_EFFORT:
                    self._queue.popleft()
                    self.n_dropped += 1
                else:
                    while len(self._queue) >= self.qos.depth \
                            and not self._closed:
                        if not self._not_full.wait(timeout=5.0):
                            # Deadlock breaker: a reliable reader that has
                            # stalled for 5 s forfeits its oldest sample.
                            self._queue.popleft()
                            self.n_dropped += 1
                            break
            self._queue.append(msg)
            self.n_received += 1
            self._not_empty.notify()

    def take(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Pop the oldest pending sample, or None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._queue:
                if deadline is None or self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._not_empty.wait(timeout=remaining)
            msg = self._queue.popleft()
            self._not_full.notify()
            return msg

    def take_all(self) -> List[Any]:
        """Drain everything pending — the batcher's bulk read."""
        with self._lock:
            msgs = list(self._queue)
            self._queue.clear()
            self._not_full.notify_all()
            return msgs

    def latest(self) -> Optional[Any]:
        """Drop all but the newest sample and return it (the reference's
        `latest_scan`/`latest_map` caching pattern, `server/.../main.py:
        77-81`, made explicit)."""
        msgs = self.take_all()
        return msgs[-1] if msgs else None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()
        self.bus._remove_subscription(self)


class Publisher:
    def __init__(self, bus: "Bus", topic: str, qos: QoSProfile):
        self.bus = bus
        self.topic = topic
        self.qos = qos
        self.n_published = 0

    def publish(self, msg: Any) -> None:
        self.n_published += 1
        self.bus._dispatch(self.topic, msg, self.qos)


class Bus:
    """One DDS domain: topic registry + delivery + fault injection.

    `drop_prob`/`reorder_prob` act on Best-Effort subscriptions only
    (Reliable delivery must never lose data) — modelling lossy Wi-Fi between
    the Pi and the PC (report.pdf §V.A).
    """

    def __init__(self, domain_id: int = 42, drop_prob: float = 0.0,
                 reorder_prob: float = 0.0, seed: int = 0):
        self.domain_id = domain_id
        self.drop_prob = drop_prob
        self.reorder_prob = reorder_prob
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Subscription]] = {}
        self._latched: Dict[str, Any] = {}
        self._reorder_hold: Dict[Tuple[int, str], Any] = {}
        #: Topics whose link is down (FaultPlan windows): publishes are
        #: dropped entirely, Reliable included — a dead transport loses
        #: everything, unlike the probabilistic Best-Effort weather.
        self._partitioned: set = set()
        self.n_partition_dropped = 0

    # -- fault injection (resilience/faultplan.py boundaries) ---------------

    def set_fault_injection(self, drop_prob: Optional[float] = None,
                            reorder_prob: Optional[float] = None) -> None:
        """Adjust the Best-Effort loss weather mid-run (FaultPlan
        drop/reorder windows). None leaves a knob unchanged."""
        with self._lock:
            if drop_prob is not None:
                self.drop_prob = drop_prob
            if reorder_prob is not None:
                self.reorder_prob = reorder_prob

    def partition(self, *topics: str) -> None:
        """Take topic links down — every publish on them vanishes until
        `heal`. The scripted stand-in for a dead sensor transport or a
        network partition between nodes."""
        with self._lock:
            self._partitioned.update(topics)

    def heal(self, *topics: str) -> None:
        """Restore partitioned topics (all of them when none named)."""
        with self._lock:
            if topics:
                self._partitioned.difference_update(topics)
            else:
                self._partitioned.clear()

    def partitioned_topics(self) -> List[str]:
        with self._lock:
            return sorted(self._partitioned)

    # -- graph construction -------------------------------------------------

    def publisher(self, topic: str, qos: QoSProfile = qos_default
                  ) -> Publisher:
        return Publisher(self, topic, qos)

    def subscribe(self, topic: str, qos: QoSProfile = qos_default,
                  callback: Optional[Callable[[Any], None]] = None
                  ) -> Subscription:
        sub = Subscription(self, topic, qos, callback)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
            latched = self._latched.get(topic)
        if latched is not None \
                and qos.durability is Durability.TRANSIENT_LOCAL:
            sub._offer(latched)
            if sub.callback is not None:
                m = sub.take()
                if m is not None:
                    sub.callback(m)
        return sub

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._subs.keys() | self._latched.keys())

    # -- delivery -----------------------------------------------------------

    def _dispatch(self, topic: str, msg: Any, pub_qos: QoSProfile) -> None:
        # One lock acquisition covers the latch write and the subscriber
        # snapshot, so a subscriber joining mid-publish cannot receive the
        # sample twice (once from the latch, once from the snapshot).
        with self._lock:
            if topic in self._partitioned:
                # Link down (FaultPlan): nothing latches, nothing
                # delivers — a dead transport, not lossy weather.
                self.n_partition_dropped += 1
                return
            if pub_qos.durability is Durability.TRANSIENT_LOCAL:
                self._latched[topic] = msg
            subs = list(self._subs.get(topic, ()))
        for sub in subs:
            delivery = [msg]
            if sub.qos.reliability is Reliability.BEST_EFFORT:
                with self._lock:
                    if self._rng.random() < self.drop_prob:
                        sub.n_dropped += 1
                        continue
                    key = (id(sub), topic)
                    if self._rng.random() < self.reorder_prob:
                        # Hold this sample; release it after the next one.
                        held = self._reorder_hold.pop(key, None)
                        self._reorder_hold[key] = msg
                        if held is None:
                            continue
                        delivery = [held]
                    else:
                        held = self._reorder_hold.pop(key, None)
                        if held is not None:
                            delivery = [msg, held]   # swapped order
            for m in delivery:
                sub._offer(m)
                if sub.callback is not None:
                    taken = sub.take()
                    if taken is not None:
                        sub.callback(taken)

    def _remove_subscription(self, sub: Subscription) -> None:
        with self._lock:
            lst = self._subs.get(sub.topic)
            if lst and sub in lst:
                lst.remove(sub)
