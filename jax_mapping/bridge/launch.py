"""Launch composition: assemble the full node graph, like the reference's
launch files.

`launch_sim_stack` is the equivalent of running BOTH
`pi_hardware.launch.py` (LiDAR driver + static TF,
`/root/reference/pi/src/thymio_project/launch/pi_hardware.launch.py`) and
`pc_server.launch.py` (SLAM + brain + API,
`/root/reference/server/thymio_project/launch/pc_server.launch.py`) against
the simulated world — one call returns a running stack with an explicit
shutdown, replacing ros2-launch orchestration (SURVEY.md §1 L5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from jax_mapping.bridge.brain import ThymioBrain, robot_ns
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import SimulatedThymioDriver
from jax_mapping.bridge.http_api import MapApiServer
from jax_mapping.bridge.mapper import MapperNode
from jax_mapping.bridge.messages import Header, TransformStamped
from jax_mapping.bridge.node import Executor
from jax_mapping.bridge.sim_node import SimNode
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig

#: Laser mount height from the reference's static TF
#: (`pi_hardware.launch.py:26-30`).
LASER_MOUNT_Z_M = 0.12


@dataclasses.dataclass
class Stack:
    """A running stack; fields are live objects."""

    cfg: SlamConfig
    bus: Bus
    tf: TfTree
    driver: SimulatedThymioDriver
    sim: SimNode
    brain: ThymioBrain
    mapper: MapperNode
    api: Optional[MapApiServer]
    executor: Executor
    voxel_mapper: Optional[object] = None    # VoxelMapperNode when depth_cam
    planner: Optional[object] = None         # PlannerNode when cfg.planner.enabled
    _steps_run: int = 0

    def run_steps(self, n: int) -> None:
        """Faster-than-realtime: drive physics+brain+mapper loops directly,
        n sensor ticks (realtime=False stacks only). The planner keeps its
        real cadence RATIO (one plan per period_s of simulated control
        time), not wall time — deterministic stepping must replan exactly
        as often as the realtime executor would."""
        steps_per_plan = max(1, round(self.cfg.planner.period_s
                                      * self.cfg.robot.control_rate_hz))
        for _ in range(n):
            self.sim.step()
            self.brain.update_loop()
            self.mapper.tick()
            if self.voxel_mapper is not None:
                self.voxel_mapper.tick()
            self._steps_run += 1
            if self.planner is not None \
                    and self._steps_run % steps_per_plan == 0:
                self.planner.tick()

    def shutdown(self) -> None:
        if self.api is not None:
            self.api.shutdown()
        self.executor.shutdown()


def launch_sim_stack(cfg: SlamConfig, world: np.ndarray,
                     world_res_m: Optional[float] = None,
                     n_robots: int = 1, http_port: Optional[int] = None,
                     realtime: bool = False,
                     drop_prob: float = 0.0, seed: int = 0,
                     depth_cam: bool = False) -> Stack:
    """Boot the whole graph. realtime=False leaves timers idle so tests can
    step deterministically via `Stack.run_steps`; realtime=True spins the
    executor thread like the reference's rclpy daemon thread
    (`server/.../main.py:285-287`). http_port=0 picks a free port.
    depth_cam=True adds the 3D pipeline: per-robot simulated depth images
    fused into a shared voxel grid (BASELINE configs[4])."""
    res = world_res_m if world_res_m is not None else cfg.grid.resolution_m
    bus = Bus(domain_id=cfg.domain_id, drop_prob=drop_prob, seed=seed)
    tf = TfTree()
    for i in range(n_robots):
        ns = robot_ns(i, n_robots)
        tf.set_static_transform(TransformStamped(
            header=Header(frame_id=f"{ns}base_link"),
            child_frame_id=f"{ns}base_laser", z=LASER_MOUNT_Z_M))

    driver = SimulatedThymioDriver(n_robots=n_robots)
    sim = SimNode(cfg, bus, driver, world, res, tf=tf,
                  rate_hz=cfg.robot.control_rate_hz, seed=seed,
                  realtime=realtime, depth_cam=depth_cam)
    brain = ThymioBrain(cfg, bus, driver, tf=tf, n_robots=n_robots)
    # Start calibrated: the odom frame origin is the boot pose; expressing
    # boot poses in the map frame up front keeps multi-robot maps aligned
    # (the fleet model's convention, models/fleet.py init_fleet_state).
    brain.poses = sim.truth_poses().copy()
    mapper = MapperNode(cfg, bus, tf=tf, n_robots=n_robots)
    for i, st in enumerate(mapper.states):
        mapper.states[i] = st._replace(pose=jnp.asarray(brain.poses[i]))

    voxel_mapper = None
    if depth_cam:
        from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
        voxel_mapper = VoxelMapperNode(cfg, bus, tf=tf, n_robots=n_robots,
                                       mapper=mapper)

    planner = None
    if cfg.planner.enabled:
        from jax_mapping.bridge.planner import PlannerNode
        planner = PlannerNode(cfg, bus, mapper=mapper, brain=brain,
                              voxel_mapper=voxel_mapper)
        if planner.voxel_mapper is not None:
            # ONE map for assignment and planning: the auction must not
            # assign frontiers whose corridors only the 3D overlay knows
            # are blocked (see mapper.publish_frontiers).
            mapper.frontier_grid_provider = planner._planning_grid

    api = None
    if http_port is not None:
        api = MapApiServer(bus, brain=brain, port=http_port,
                           mapper=mapper, voxel_mapper=voxel_mapper,
                           planner=planner)
        api.serve_thread()

    nodes = [sim, brain, mapper] + \
        ([voxel_mapper] if voxel_mapper is not None else []) + \
        ([planner] if planner is not None else [])
    executor = Executor(nodes)
    if realtime:
        executor.spin_thread()
    return Stack(cfg=cfg, bus=bus, tf=tf, driver=driver, sim=sim,
                 brain=brain, mapper=mapper, api=api, executor=executor,
                 voxel_mapper=voxel_mapper, planner=planner)
