"""Launch composition: assemble the full node graph, like the reference's
launch files.

`launch_sim_stack` is the equivalent of running BOTH
`pi_hardware.launch.py` (LiDAR driver + static TF,
`/root/reference/pi/src/thymio_project/launch/pi_hardware.launch.py`) and
`pc_server.launch.py` (SLAM + brain + API,
`/root/reference/server/thymio_project/launch/pc_server.launch.py`) against
the simulated world — one call returns a running stack with an explicit
shutdown, replacing ros2-launch orchestration (SURVEY.md §1 L5).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Set

import jax.numpy as jnp
import numpy as np

from jax_mapping.bridge.brain import ThymioBrain, robot_ns
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.driver import SimulatedThymioDriver
from jax_mapping.bridge.http_api import MapApiServer
from jax_mapping.bridge.mapper import MapperNode
from jax_mapping.bridge.messages import Header, TransformStamped
from jax_mapping.bridge.node import Executor
from jax_mapping.bridge.sim_node import SimNode
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig
from jax_mapping.resilience.health import FleetHealth
from jax_mapping.resilience.supervisor import Supervisor

#: Laser mount height from the reference's static TF
#: (`pi_hardware.launch.py:26-30`).
LASER_MOUNT_Z_M = 0.12


@dataclasses.dataclass
class Stack:
    """A running stack; fields are live objects."""

    cfg: SlamConfig
    bus: Bus
    tf: TfTree
    driver: SimulatedThymioDriver
    sim: SimNode
    brain: ThymioBrain
    mapper: MapperNode
    api: Optional[MapApiServer]
    executor: Executor
    voxel_mapper: Optional[object] = None    # VoxelMapperNode when depth_cam
    planner: Optional[object] = None         # PlannerNode when cfg.planner.enabled
    health: Optional[FleetHealth] = None     # shared degraded-mode registry
    supervisor: Optional[Supervisor] = None  # heartbeat watch + restarts
    recovery: Optional[object] = None        # estimator guardrails (RecoveryManager)
    fault_plan: Optional[object] = None      # attached FaultPlan, if any
    #: Causal tracing (obs/trace.Tracer) when ObsConfig.enabled; the
    #: same object rides bus.tracer — this field is the test/operator
    #: handle (span export, /trace backs onto it through the bus).
    tracer: Optional[object] = None
    #: Pipeline latency ledger (obs/pipeline.PipelineLedger) when
    #: ObsConfig.enabled: per-revision scan→served waypoint stamps,
    #: exported on /metrics + /status and carried as the `pipeline`
    #: section of flight-recorder dumps (critical-path CLI input).
    pipeline: Optional[object] = None
    #: Freshness SLO engine (obs/slo.SloEngine) when ObsConfig.enabled
    #: and objectives are declared in ObsConfig.slo — evaluated once
    #: per mapper tick, alerts flight-recorded + on /status.slo.
    slo: Optional[object] = None
    #: Dispatch profiler (obs/devprof.DispatchProfiler) when
    #: ObsConfig.devprof.enabled — wraps the jitted entry points
    #: process-wide; shutdown() uninstalls so a later stack can own
    #: the wrappers.
    devprof: Optional[object] = None
    #: Auto-checkpoint file the supervisor saves to / resumes the mapper
    #: from ("" = auto-checkpointing disabled; pass checkpoint_dir to
    #: launch_sim_stack to enable).
    auto_checkpoint_path: str = ""
    #: Bounded-memory world spill directory ("" = host-LRU only): where
    #: evicted tiles overflow to disk when cfg.world.windowed; a
    #: restarted MapperNode reopens the SAME spill file so tiles
    #: evicted before the crash rehydrate after it.
    world_spill_dir: str = ""
    #: Warm-restart storage tier (io/compile_cache.CompileCacheManager)
    #: when ColdStartConfig.enabled — persistent XLA cache, AOT
    #: snapshots, the cache_wipe fault boundary. None = cold restarts.
    compile_cache: Optional[object] = None
    #: The staged warm-up state machine (resilience/warmup.StagedWarmup)
    #: driving restart_mapper's restore→warm→ready ladder; constructed
    #: lazily on the first restart when launch didn't build one.
    warmup: Optional[object] = None
    #: Test seam: called with the stack DURING the warming stage of a
    #: staged restart (serving still answering from the prior epoch
    #: with state=warming) — the degraded-serving-window assertion
    #: hook. Exceptions are contained; the restart proceeds.
    warmup_hook: Optional[object] = None
    #: Mission multi-tenancy control plane
    #: (tenancy/controlplane.TenantControlPlane) when
    #: TenancyConfig.enabled — admit/evict megabatched model-level
    #: missions alongside this bridge stack; None = no tenancy.
    tenancy: Optional[object] = None
    _killed: Set[str] = dataclasses.field(default_factory=set)
    _steps_run: int = 0

    def run_steps(self, n: int) -> None:
        """Faster-than-realtime: drive physics+brain+mapper loops directly,
        n sensor ticks (realtime=False stacks only). The planner keeps its
        real cadence RATIO (one plan per period_s of simulated control
        time), not wall time — deterministic stepping must replan exactly
        as often as the realtime executor would.

        An attached FaultPlan fires before each step on the step index;
        the supervisor ticks once per step AFTER the nodes, so a node
        killed at step k misses its k-th beat and the dead-declaration
        countdown starts the same step — deterministic chaos."""
        steps_per_plan = max(1, round(self.cfg.planner.period_s
                                      * self.cfg.robot.control_rate_hz))
        for _ in range(n):
            if self.fault_plan is not None:
                self.fault_plan.apply(self, self._steps_run)
            self.sim.step()
            if "thymio_brain" not in self._killed:
                self.brain.update_loop()
            if "jax_mapper" not in self._killed:
                self.mapper.tick()
            if self.voxel_mapper is not None:
                self.voxel_mapper.tick()
            self._steps_run += 1
            if self.planner is not None \
                    and self._steps_run % steps_per_plan == 0:
                self.planner.tick()
            if self.supervisor is not None:
                self.supervisor.tick()

    # -- resilience surface (supervisor / FaultPlan boundaries) -------------

    def attach_fault_plan(self, plan) -> None:
        """Arm a FaultPlan: `run_steps` applies it on the step clock."""
        self.fault_plan = plan

    def kill_node(self, name: str) -> None:
        """Destroy a node mid-mission (FaultPlan `kill_node`): timers
        cancelled, subscriptions closed, its deterministic tick skipped.
        The supervisor notices the silent heartbeat and restarts it."""
        node = {"thymio_brain": self.brain,
                "jax_mapper": self.mapper}.get(name)
        if node is None:
            raise ValueError(f"kill_node: unknown node {name!r}")
        self._killed.add(name)
        node.destroy()

    def save_auto_checkpoint(self) -> None:
        """The supervisor's checkpoint cadence hook: snapshot the mapper
        to `auto_checkpoint_path` (save_checkpoint rotates the previous
        generation to the .prev slot — the corruption fallback). A
        journal-armed tenancy plane checkpoints its live tenants on the
        same cadence — the durability heartbeat `restore()` replays."""
        from jax_mapping.io.checkpoint import (
            clear_world_sidecar, previous_checkpoint_path,
            save_checkpoint, save_world_sidecar, world_sidecar_path)
        os.makedirs(os.path.dirname(self.auto_checkpoint_path),
                    exist_ok=True)
        world = getattr(self.mapper, "world", None)
        if world is not None:
            # Rotate the window manifest in LOCKSTEP with
            # save_checkpoint's current -> .prev rotation: a corrupt
            # primary falls back to the .prev STATES, which must
            # re-anchor from the manifest saved with them — a newer
            # origin under older tiles is silent map corruption.
            wp = world_sidecar_path(self.auto_checkpoint_path)
            if os.path.exists(wp):
                os.replace(wp, world_sidecar_path(
                    previous_checkpoint_path(self.auto_checkpoint_path)))
        save_checkpoint(
            self.auto_checkpoint_path, self.mapper.snapshot_states(),
            config_json=self.cfg.to_json(),
            retain_generations=self.cfg.resilience
            .checkpoint_retain_generations)
        if world is not None:
            save_world_sidecar(self.auto_checkpoint_path,
                               world.checkpoint_payload(),
                               config_json=self.cfg.to_json())
        else:
            clear_world_sidecar(self.auto_checkpoint_path)
        if self.tenancy is not None:
            self.tenancy.checkpoint_all()

    def crash_controlplane(self) -> dict:
        """Kill the tenancy control plane and rebuild it from its
        journal + checkpoints (the `controlplane_crash` FaultPlan kind
        and the supervisor-restart durability contract): the in-memory
        registry is dropped wholesale, a NEW plane replays
        snapshot+journal via `restore()`, and the API swaps to it
        atomically — the same tenant set comes back with every epoch
        advanced, so live `/tiles?tenant=` clients resync instead of
        seeing revision regressions. Returns the restore report."""
        old = self.tenancy
        if old is None:
            raise ValueError("crash_controlplane: no tenancy plane "
                             "on this stack")
        old.checkpoint_all()
        from jax_mapping.tenancy import TenantControlPlane
        plane = TenantControlPlane(
            self.cfg, world_res_m=old.world_res_m,
            checkpoint_dir=old.checkpoint_dir,
            compile_cache=self.compile_cache, devprof=self.devprof,
            pipeline=self.pipeline)
        report = plane.restore()
        self.tenancy = plane
        if self.api is not None:
            self.api.tenancy = plane
        return report

    def restart_mapper(self) -> None:
        """The supervisor's mapper restarter: a STAGED warm-up (ISSUE
        12), not a cold boot — rebuild the MapperNode, resume it from
        the latest auto-checkpoint with pose re-anchoring, pre-warm the
        jitted entry points in priority order from the cold-start warm
        tiers, and only then swap the node into the executor/API (which
        is what re-admits it: the supervisor's fresh heartbeat grace
        starts when this restarter returns, and FleetHealth-driven
        assignment resumes with the new node). While the warm-up runs,
        the API keeps answering from the OLD node's last epoch with
        `state=warming` instead of blocking — availability over
        freshness, the degraded-serving contract.

        The crash-mid-mission contract (SURVEY.md §5's gap): the map
        resumes from the newest intact checkpoint generation
        (`load_checkpoint_with_fallback` degrades to the rotated
        last-good file when the newest is corrupt — and now records
        WHICH slot it chose), and each robot's chain re-anchors at the
        BRAIN's live pose — odometry kept integrating while the mapper
        was down, so the checkpointed endpoint poses are stale; fusing
        at them would smear the resumed map. No checkpoint at all
        degrades to a blank map, still anchored at the live poses."""
        if self.warmup is None:
            from jax_mapping.resilience.warmup import StagedWarmup
            self.warmup = StagedWarmup(cache=self.compile_cache,
                                       devprof=self.devprof)
        wu = self.warmup
        if self.api is not None:
            self.api.set_warming(True)
        try:
            self._restart_mapper_staged(wu)
        finally:
            if self.api is not None:
                self.api.set_warming(False)
        wu.mark_ready()

    def _restart_mapper_staged(self, wu) -> None:
        n = self.mapper.n_robots
        old = self.mapper
        old.destroy()
        wu.begin_restore()
        states = None
        used_path = None
        if self.auto_checkpoint_path:
            from jax_mapping.io.checkpoint import (
                CheckpointCorrupt, load_checkpoint_with_fallback)
            from jax_mapping.models import slam as _S
            mcfg = self.cfg
            if mcfg.world.windowed:
                # Windowed checkpoints carry WINDOW-shaped states (the
                # mapper's device config) under the full logical
                # config_json — the template must match the arrays, not
                # the logical extent (io/checkpoint shape checks).
                from jax_mapping.world.store import window_slam_config
                mcfg = window_slam_config(mcfg)
            template = [_S.init_state(mcfg) for _ in range(n)]
            try:
                states, _cfg_json, used_path = \
                    load_checkpoint_with_fallback(
                        self.auto_checkpoint_path, template)
            except (FileNotFoundError, CheckpointCorrupt):
                states = None                # no intact generation: blank
        # Pre-warm BEFORE the new node enters service: entry points
        # warm fusion-first (time-to-first-fused-scan is the
        # availability metric) from AOT snapshots, then the persistent
        # cache, then cold compile; an in-process restart (jit caches
        # survived) skips in O(registry) time. The profiler re-baselines
        # inside prewarm so warm-tier variants never count as live
        # recompiles.
        wu.begin_warming()
        sigs = self.devprof.signatures() if self.devprof is not None \
            else {}
        wu.prewarm(sigs)
        if self.warmup_hook is not None:
            # Test seam: observe the degraded-serving window (prior
            # epoch content + state=warming) from inside it.
            try:
                self.warmup_hook(self)
            except Exception:                # noqa: BLE001
                import traceback
                traceback.print_exc()
        new = MapperNode(self.cfg, self.bus, tf=self.tf, n_robots=n,
                         health=self.health, recovery=self.recovery,
                         pipeline=self.pipeline, slo=self.slo,
                         spill_dir=self.world_spill_dir or None)
        # Serving restart epoch: the resumed node legitimately re-serves
        # an OLDER map_revision (checkpoints lag the live map); the
        # bumped epoch tells delta clients to drop their cache and
        # resync full instead of raising a revision regression.
        new.restart_epoch = old.restart_epoch + 1
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("restart_epoch", node="jax_mapper",
                               epoch=new.restart_epoch,
                               resumed_from_checkpoint=states is not None)
        anchors = self.brain.poses.copy()
        if states is not None:
            if new.world is not None:
                # Re-anchor the window BEFORE the states install: the
                # checkpointed grid is window content AT the manifest's
                # origin, and the brain's world-frame anchor poses must
                # convert to the robocentric window frame (world =
                # window + offset). A missing/corrupt/drifted manifest
                # degrades to the boot origin — flight-recorded, never
                # a crashed restart (the checkpoint states still load;
                # only spilled-tile provenance is lost).
                from jax_mapping.io.checkpoint import load_world_sidecar
                try:
                    payload = load_world_sidecar(
                        used_path, running_config_json=self.cfg.to_json())
                except Exception as e:       # noqa: BLE001
                    payload = None
                    flight_recorder.record(
                        "world_sidecar_degraded", node="jax_mapper",
                        error=f"{type(e).__name__}: {e}")
                if payload is not None:
                    new.world.restore_payload(payload)
                anchors[:, :2] -= new.world.offset_xy()[None, :]
            new.restore_states(states, anchor_poses=anchors)
        else:
            for i, st in enumerate(new.states):
                new.states[i] = st._replace(pose=jnp.asarray(anchors[i]))
        # Re-wire every holder of the old node (the launch-time graph).
        self.mapper = new
        self.executor.nodes = [new if nd is old else nd
                               for nd in self.executor.nodes]
        if self.planner is not None:
            self.planner.mapper = new
            if getattr(self.planner, "voxel_mapper", None) is not None:
                new.frontier_grid_provider = self.planner._planning_grid
                new.frontier_grid_key_provider = self.planner.overlay_key
        if self.voxel_mapper is not None:
            self.voxel_mapper.mapper = new
        if self.api is not None:
            # rebind_mapper (not a bare attribute swap): the serving
            # tile stores and revision listener close over the mapper
            # they were built with — leaving them on the destroyed node
            # would serve its final map forever.
            self.api.rebind_mapper(new)
        self._killed.discard("jax_mapper")

    def save_compile_snapshots(self) -> dict:
        """Serialize AOT executable snapshots for every (function,
        captured signature) the dispatch profiler observed — the warm
        half of the restart bench. EXPLICIT only (CLI / bench / tests —
        the cost-ledger collection doctrine: never a supervisor-cadence
        side effect); needs both the cold-start tier and an armed
        profiler, else reports an empty pass."""
        if self.compile_cache is None or self.devprof is None:
            return {"n_saved": 0, "n_failed": 0, "n_uncallable": 0,
                    "names": []}
        return self.compile_cache.save_aot(self.devprof.signatures(),
                                           resolve=self.devprof.raw_fn)

    def shutdown(self) -> None:
        if self.api is not None:
            self.api.shutdown()
        self.executor.shutdown()
        if self.compile_cache is not None:
            # Warm pool BEFORE devprof: a pool installed during a
            # staged restart wraps the already-installed profiler
            # wrapper, and uninstalling the profiler first would find
            # no site holding it (the shutdown-leak case). The pool's
            # uninstall splices itself out of either nesting; the
            # profiler then restores cleanly.
            self.compile_cache.pool.uninstall()
            self.compile_cache.disable()
        if self.devprof is not None:
            # After the HTTP plane and executor stop: no worker thread
            # is mid-dispatch through a wrapper being unbound.
            self.devprof.uninstall()


def launch_sim_stack(cfg: SlamConfig, world: np.ndarray,
                     world_res_m: Optional[float] = None,
                     n_robots: int = 1, http_port: Optional[int] = None,
                     realtime: bool = False,
                     drop_prob: float = 0.0, seed: int = 0,
                     depth_cam: bool = False,
                     checkpoint_dir: Optional[str] = None) -> Stack:
    """Boot the whole graph. realtime=False leaves timers idle so tests can
    step deterministically via `Stack.run_steps`; realtime=True spins the
    executor thread like the reference's rclpy daemon thread
    (`server/.../main.py:285-287`). http_port=0 picks a free port.
    depth_cam=True adds the 3D pipeline: per-robot simulated depth images
    fused into a shared voxel grid (BASELINE configs[4]).
    checkpoint_dir arms the supervisor's auto-checkpoint cadence (and
    therefore restart-from-checkpoint); None keeps the stack disk-free."""
    res = world_res_m if world_res_m is not None else cfg.grid.resolution_m
    compile_cache = None
    if cfg.cold_start.enabled:
        # Warm-restart storage tier (ISSUE 12): the persistent compile
        # cache must attach BEFORE the first jit compile below so every
        # compile this launch pays is persisted for the next process.
        # Failures degrade to plain recompile (flight-recorder event),
        # never block the launch.
        cache_root = cfg.cold_start.cache_dir or (
            os.path.join(checkpoint_dir, "compile_cache")
            if checkpoint_dir else "")
        if cache_root:
            from jax_mapping.io.compile_cache import CompileCacheManager
            compile_cache = CompileCacheManager(
                cfg.cold_start, cache_root, config_json=cfg.to_json())
            compile_cache.enable()
            compile_cache.evict_lru()
    tracer = None
    pipeline = None
    slo = None
    if cfg.obs.enabled:
        # Causal tracing (obs/): deterministic trace ids derived from
        # (this seed, topic, per-topic publish seq) — two same-seed
        # deterministic runs emit identical streams. enabled=False
        # constructs nothing: the bus hot path is bit-exact pre-obs.
        from jax_mapping.obs import Tracer
        tracer = Tracer(seed=seed, capacity=cfg.obs.trace_ring)
        # Freshness tier (obs/pipeline.py, obs/slo.py): the ledger
        # rides the tracing gate (per-revision scan→served waypoint
        # stamps, host-side bookkeeping only); the SLO engine only
        # exists when objectives are declared. enabled=False
        # constructs NEITHER — bit-exact, the ObsConfig doctrine.
        from jax_mapping.obs.pipeline import PipelineLedger
        pipeline = PipelineLedger()
        if cfg.obs.slo:
            from jax_mapping.obs.slo import SloEngine
            slo = SloEngine(cfg.obs.slo, pipeline=pipeline)
    devprof = None
    if cfg.obs.devprof.enabled:
        # Device-side dispatch profiling (obs/devprof.py): wraps the
        # jitted entry points process-wide — constructed here but
        # INSTALLED at the end of launch, after every lazily-imported
        # subsystem (serving, recovery) has pulled in its modules, so
        # no entry point dodges the wrapper. enabled=False constructs
        # nothing: the dispatch path is bit-exact pre-devprof.
        from jax_mapping.obs.devprof import DispatchProfiler
        devprof = DispatchProfiler(cfg.obs.devprof, tracer=tracer)
    # The always-on flight recorder follows the newest stack: dumps go
    # to a `postmortem/` subdir of its checkpoint dir (None = events
    # only, no files; the subdir keeps MissionReport.checkpoint_files
    # and generation GC blind to dump artifacts) and include its
    # tracer's spans when tracing is armed.
    from jax_mapping.obs.recorder import flight_recorder
    flight_recorder.configure(
        dump_dir=(os.path.join(checkpoint_dir, "postmortem")
                  if checkpoint_dir else None),
        tracer=tracer, capacity=cfg.obs.recorder_ring,
        pipeline=pipeline)
    bus = Bus(domain_id=cfg.domain_id, drop_prob=drop_prob, seed=seed,
              tracer=tracer)
    tf = TfTree()
    for i in range(n_robots):
        ns = robot_ns(i, n_robots)
        tf.set_static_transform(TransformStamped(
            header=Header(frame_id=f"{ns}base_link"),
            child_frame_id=f"{ns}base_laser", z=LASER_MOUNT_Z_M))

    driver = SimulatedThymioDriver(n_robots=n_robots)
    sim = SimNode(cfg, bus, driver, world, res, tf=tf,
                  rate_hz=cfg.robot.control_rate_hz, seed=seed,
                  realtime=realtime, depth_cam=depth_cam)
    health = (FleetHealth(cfg.resilience, n_robots)
              if cfg.resilience.enabled else None)
    recovery = None
    if cfg.recovery.enabled and health is not None:
        # Estimator guardrails (recovery/): ONE manager shared by the
        # brain (anti-stuck ladder, blacklist clock), the mapper
        # (watchdog feed, quarantine + relocalization, blacklist
        # post-pass) and the HTTP plane (export) — the FleetHealth
        # wiring pattern. enabled=False keeps every node on its
        # pre-guardrail path exactly. The guardrails ACT through the
        # health ladder (coast, LED, frontier reassignment, /status),
        # so they require resilience: quarantining a robot nobody
        # coasts or reassigns would silently stall exploration with no
        # operator-visible signal.
        from jax_mapping.recovery import RecoveryManager
        recovery = RecoveryManager(cfg.recovery, n_robots,
                                   robot=cfg.robot)
    brain = ThymioBrain(cfg, bus, driver, tf=tf, n_robots=n_robots,
                        health=health, recovery=recovery)
    # Start calibrated: the odom frame origin is the boot pose; expressing
    # boot poses in the map frame up front keeps multi-robot maps aligned
    # (the fleet model's convention, models/fleet.py init_fleet_state).
    brain.poses = sim.truth_poses().copy()
    # Bounded-memory world spill tier: evicted tiles overflow to disk
    # under the checkpoint dir (surviving mapper restarts); a disk-free
    # stack keeps the host LRU only and sheds beyond it.
    world_spill_dir = (os.path.join(checkpoint_dir, "world_spill")
                       if checkpoint_dir and cfg.world.windowed else "")
    mapper = MapperNode(cfg, bus, tf=tf, n_robots=n_robots, health=health,
                        recovery=recovery, pipeline=pipeline, slo=slo,
                        spill_dir=world_spill_dir or None)
    for i, st in enumerate(mapper.states):
        mapper.states[i] = st._replace(pose=jnp.asarray(brain.poses[i]))

    voxel_mapper = None
    if depth_cam:
        from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
        voxel_mapper = VoxelMapperNode(cfg, bus, tf=tf, n_robots=n_robots,
                                       mapper=mapper)

    planner = None
    if cfg.planner.enabled:
        from jax_mapping.bridge.planner import PlannerNode
        planner = PlannerNode(cfg, bus, mapper=mapper, brain=brain,
                              voxel_mapper=voxel_mapper, health=health)
        if planner.voxel_mapper is not None:
            # ONE map for assignment and planning: the auction must not
            # assign frontiers whose corridors only the 3D overlay knows
            # are blocked (see mapper.publish_frontiers).
            mapper.frontier_grid_provider = planner._planning_grid
            # The overlay's content key: lets the incremental frontier
            # pipeline keep its tile cache across publishes where only
            # the 2D map moved (mapper._frontier_basis).
            mapper.frontier_grid_key_provider = planner.overlay_key

    supervisor = None
    if cfg.resilience.enabled:
        # Supervisor ticks at the CONTROL rate, matching the 1:1
        # supervisor-tick-per-step cadence of deterministic run_steps:
        # missed-beat thresholds then mean "control periods" in both
        # modes. A fixed fast tick would declare slow-platform nodes
        # (low control_rate_hz) perpetually dead in realtime stacks.
        supervisor = Supervisor(cfg.resilience, bus, seed=seed,
                                tick_period_s=1.0
                                / cfg.robot.control_rate_hz)

    api = None
    if http_port is not None:
        api = MapApiServer(bus, brain=brain, port=http_port,
                           mapper=mapper, voxel_mapper=voxel_mapper,
                           planner=planner, health=health,
                           supervisor=supervisor, recovery=recovery,
                           devprof=devprof, pipeline=pipeline, slo=slo,
                           lock_timeout_s=cfg.resilience.http_lock_timeout_s)
        api.serve_thread()

    nodes = [sim, brain, mapper] + \
        ([voxel_mapper] if voxel_mapper is not None else []) + \
        ([planner] if planner is not None else []) + \
        ([supervisor] if supervisor is not None else [])
    executor = Executor(nodes)
    warmup = None
    if compile_cache is not None:
        # Launch-time staged warm-up (the resume-process path): load
        # any AOT snapshots for this fingerprint into the warm pool and
        # pre-warm the captured entry points through the cache ladder —
        # BEFORE devprof installs, so the profiler's recompile baseline
        # lands on the post-warm-up cache sizes (a warm boot must not
        # report its cold-start repayment as live recompiles).
        from jax_mapping.resilience.warmup import StagedWarmup
        warmup = StagedWarmup(cache=compile_cache, devprof=devprof)
        if cfg.cold_start.prewarm_on_launch:
            warmup.begin_warming()
            warmup.prewarm()
            warmup.mark_ready()
    if devprof is not None:
        devprof.install()
    stack = Stack(cfg=cfg, bus=bus, tf=tf, driver=driver, sim=sim,
                  brain=brain, mapper=mapper, api=api, executor=executor,
                  voxel_mapper=voxel_mapper, planner=planner,
                  health=health, supervisor=supervisor, recovery=recovery,
                  tracer=tracer, devprof=devprof, pipeline=pipeline,
                  slo=slo, compile_cache=compile_cache, warmup=warmup,
                  world_spill_dir=world_spill_dir)
    if cfg.tenancy.enabled:
        # Mission multi-tenancy (tenancy/): the control plane that
        # admits/evicts megabatched model-level missions alongside
        # this bridge stack, sharing its warm-restart storage tier and
        # dispatch profiler. `enabled=False` constructs NOTHING — no
        # plane, no batch, no megabatch trace; bit-exact pre-tenancy.
        from jax_mapping.tenancy import TenantControlPlane
        stack.tenancy = TenantControlPlane(
            cfg, world_res_m=res,
            checkpoint_dir=(os.path.join(checkpoint_dir, "tenants")
                            if checkpoint_dir else None),
            compile_cache=compile_cache, devprof=devprof,
            pipeline=pipeline)
        if api is not None:
            api.tenancy = stack.tenancy
    if api is not None and (compile_cache is not None
                            or warmup is not None):
        # /status `cold_start` export: cache counters, warm-pool stats,
        # the warm-up report (closure over the stack so a later staged
        # restart's state shows live).
        api.coldstart_status = lambda: {
            "cache": (stack.compile_cache.status()
                      if stack.compile_cache is not None else None),
            "warmup": (stack.warmup.snapshot()
                       if stack.warmup is not None else None),
        }
    if supervisor is not None:
        # Registration needs the Stack (restarter + checkpointer close
        # over it), so it happens after construction. The brain has no
        # restarter — its process-local state (driver link, poses) can't
        # be rebuilt from a checkpoint; death is declared and exported.
        supervisor.register("thymio_brain")
        supervisor.register("jax_mapper", stack.restart_mapper)
        if checkpoint_dir is not None:
            stack.auto_checkpoint_path = os.path.join(
                checkpoint_dir, "auto_checkpoint.npz")
            supervisor.attach_checkpointer(stack.save_auto_checkpoint)
    if realtime:
        executor.spin_thread()
    return stack
