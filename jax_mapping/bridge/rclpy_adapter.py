"""Real ROS 2 boundary: bridge the in-process Bus to rclpy topics.

SURVEY.md §7's design stance — "keep the ROS 2 node graph as the plugin
boundary so the Thymio bridge, Nav2, and RViz remain untouched" — lands
here. The framework's whole graph runs on the in-process Bus (bridge/bus.py)
so it is testable anywhere; when rclpy IS installed, this adapter mirrors
the reference's exact topic surface onto real DDS:

  outbound (Bus -> ROS):  /map, /map_updates (nav_msgs/OccupancyGrid,
                          `server/rviz_config.rviz:152-165`),
                          /pose (geometry_msgs/PoseWithCovarianceStamped,
                          rviz_config.rviz:133-143) + /poses (PoseArray,
                          whole fleet),
                          /scan, /odom — per robot namespace for fleets:
                          /robot<i>/scan, /robot<i>/odom (brain.robot_ns;
                          plain /scan /odom for one robot, rviz:94-106,
                          main.py:217-224),
                          /frontiers_markers (visualization_msgs/
                          MarkerArray of clustered frontier goals — the
                          bundled RViz config's Frontiers display),
                          /voxel_points (sensor_msgs/PointCloud2 of the
                          3D voxel map's occupied centres; inert unless
                          the stack runs a voxel mapper),
                          /tf (tf2_ros broadcaster, main.py:202-215)
  inbound  (ROS -> Bus):  /cmd_vel (geometry_msgs/Twist — Nav2 or
                          teleop_twist_joy, report.pdf §III.A),
                          /initialpose + /goal_pose (RViz tools),
                          and optionally per-namespace /scan + /odom
                          (live-hardware mode: real ldlidar_stl_ros2
                          drivers feed the mapper)

so RViz with `configs/jax_mapping.rviz` and Nav2 subscribe/publish exactly
the contracts the reference wires up in
`server/thymio_project/launch/pc_server.launch.py:12-34`.

Import-guarded: everything degrades to a clear RuntimeError when rclpy is
absent (this image has no ROS); CI exercises the adapter against a stub
rclpy module (tests/test_rclpy_adapter.py).
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, List, Optional

import numpy as np

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import (
    Header, LaserScan, OccupancyGrid, Odometry, Twist,
)
from jax_mapping.bridge.qos import Reliability
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig


def rclpy_available() -> bool:
    """True when the real ROS 2 python stack can be imported."""
    try:
        import rclpy  # noqa: F401
        import rclpy.node  # noqa: F401
        return True
    except Exception:
        return False


def _to_ros_time(TimeCls, stamp: float):
    sec = int(stamp)
    return TimeCls(sec=sec, nanosec=int((stamp - sec) * 1e9))


def _from_ros_time(t) -> float:
    return float(t.sec) + float(t.nanosec) * 1e-9


def _yaw_from_quat(q) -> float:
    """Planar yaw from a ROS quaternion (x/y ignored: yaw-only maps)."""
    return 2.0 * math.atan2(float(q.z), float(q.w))


class RclpyAdapter:
    """One rclpy node pair of publishers/subscriptions mirroring the Bus.

    Args:
      bus: the in-process Bus carrying the framework graph.
      cfg: SlamConfig (QoS + rates: scan is Best-Effort per report.pdf
        §V.A; /map latches transient-local for late-joining RViz).
      tf: TfTree to broadcast (map->odom, odom->base_link, static laser
        mount) at cfg.tf_publish_period_s.
      outbound: Bus topics re-published into ROS.
      inbound: ROS topics re-published onto the Bus.
      node_name: ROS node name.
      n_robots: fleet size; scan/odom bridge per robot namespace
        ("/scan" for one robot, "/robot<i>/scan" for fleets — the same
        brain.robot_ns convention the internal graph uses).
    """

    OUTBOUND_DEFAULT = ("map", "map_updates", "pose", "scan", "odom",
                        "frontiers", "voxel_points", "plan", "graph")
    INBOUND_DEFAULT = ("cmd_vel", "initialpose", "goal_pose")

    def __init__(self, bus: Bus, cfg: SlamConfig,
                 tf: Optional[TfTree] = None,
                 outbound: Iterable[str] = OUTBOUND_DEFAULT,
                 inbound: Iterable[str] = INBOUND_DEFAULT,
                 node_name: str = "jax_mapping_bridge",
                 n_robots: int = 1):
        if not rclpy_available():
            raise RuntimeError(
                "rclpy is not importable — the ROS 2 adapter needs a sourced "
                "ROS 2 (Jazzy) environment; see README 'ROS 2 / RViz'. The "
                "rest of the framework runs without it.")
        import rclpy
        from rclpy.node import Node as RosNode

        self.bus = bus
        self.cfg = cfg
        self.tf = tf
        self.n_robots = max(1, n_robots)
        self._subs: List = []
        self._spin_thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()

        if not rclpy.ok():
            rclpy.init()
        self.node: "RosNode" = RosNode(node_name)

        self._msgs = self._import_msgs()
        self._wire_outbound(set(outbound))
        self._wire_inbound(set(inbound))
        if tf is not None:
            self._wire_tf()

    # -- wiring -------------------------------------------------------------

    @staticmethod
    def _import_msgs():
        import geometry_msgs.msg as geo
        import nav_msgs.msg as nav
        import sensor_msgs.msg as sen
        import builtin_interfaces.msg as bi
        try:
            # common_interfaces ships it everywhere RViz runs, but a
            # stripped ros-base without it only loses the marker display.
            import visualization_msgs.msg as vis
        except Exception:
            vis = None
        try:
            # rviz_default_plugins depends on map_msgs; its absence only
            # downgrades /map_updates to full-grid republish.
            import map_msgs.msg as map_msgs
        except Exception:
            map_msgs = None
        return {"geo": geo, "nav": nav, "sen": sen, "bi": bi, "vis": vis,
                "map_msgs": map_msgs}

    def _ros_qos(self, *, best_effort: bool = False, latched: bool = False,
                 depth: int = 10):
        from rclpy.qos import (
            DurabilityPolicy, QoSProfile, ReliabilityPolicy,
        )
        return QoSProfile(
            depth=depth,
            reliability=(ReliabilityPolicy.BEST_EFFORT if best_effort
                         else ReliabilityPolicy.RELIABLE),
            durability=(DurabilityPolicy.TRANSIENT_LOCAL if latched
                        else DurabilityPolicy.VOLATILE),
        )

    # Bus-side topic names for each logical topic. The internal graph's
    # names are uneven on purpose-mirroring-the-reference grounds: the
    # mapper publishes slashed absolute names ("/map", "/pose"), while
    # per-robot sensor topics are namespaced UNslashed ("scan",
    # "robot0/scan" — brain.robot_ns). The adapter must use the exact
    # strings (Bus lookups are exact; tests/test_stack.py pins the graph).
    BUS_TOPICS = {
        "map": "/map", "map_updates": "/map_updates", "pose": "/pose",
        "frontiers": "/frontiers", "cmd_vel": "/cmd_vel",
        "initialpose": "/initialpose", "goal_pose": "/goal_pose",
        "scan": "scan", "odom": "odom",
        "voxel_points": "/voxel_points",
        "plan": "/plan",
        "graph": "/graph",
    }

    def _wire_outbound(self, topics) -> None:
        nav = self._msgs["nav"]
        geo = self._msgs["geo"]
        sen = self._msgs["sen"]
        n = self.node
        if "map" in topics:
            pub = n.create_publisher(nav.OccupancyGrid, "/map",
                                     self._ros_qos(latched=True, depth=1))
            self._bus_to_ros("map", pub, self.occupancy_to_ros)
        if "map_updates" in topics:
            # RViz's Map display reads map_msgs/OccupancyGridUpdate on its
            # update topic; publishing a full OccupancyGrid there is a
            # silent type clash. Convert when map_msgs is available.
            if self._msgs["map_msgs"] is not None:
                pub = n.create_publisher(
                    self._msgs["map_msgs"].OccupancyGridUpdate,
                    "/map_updates", self._ros_qos(depth=1))
                self._bus_to_ros("map_updates", pub,
                                 self.occupancy_to_ros_update)
            else:
                pub = n.create_publisher(nav.OccupancyGrid, "/map_updates",
                                         self._ros_qos(depth=1))
                self._bus_to_ros("map_updates", pub, self.occupancy_to_ros)
        if "pose" in topics:
            pub = n.create_publisher(geo.PoseWithCovarianceStamped, "/pose",
                                     self._ros_qos())
            self._bus_to_ros("pose", pub, self.pose_list_to_ros)
            pub_all = n.create_publisher(geo.PoseArray, "/poses",
                                         self._ros_qos())
            self._bus_to_ros("pose", pub_all, self.pose_list_to_ros_array)
        if "frontiers" in topics and self._msgs["vis"] is not None:
            # The bundled RViz config's MarkerArray display
            # (configs/jax_mapping.rviz "/frontiers_markers") reads this.
            pub = n.create_publisher(self._msgs["vis"].MarkerArray,
                                     "/frontiers_markers",
                                     self._ros_qos(depth=1))
            self._bus_to_ros("frontiers", pub, self.frontiers_to_ros_markers)
        if "plan" in topics:
            # The global planner's path (bridge/planner.py) on the topic
            # Nav2's planners use; RViz's Path display reads it.
            pub = n.create_publisher(nav.Path, "/plan",
                                     self._ros_qos(depth=1))
            self._bus_to_ros("plan", pub, self.path_to_ros)
        if "graph" in topics and self._msgs["vis"] is not None:
            # The pose graph as markers (slam_toolbox's interactive-mode
            # graph view); same MarkerArray class as the frontier layer.
            pub = n.create_publisher(self._msgs["vis"].MarkerArray,
                                     "/graph", self._ros_qos(depth=1))
            self._bus_to_ros("graph", pub, self.graph_to_ros_markers)
        if "voxel_points" in topics:
            # The 3D voxel map as a point cloud (RViz PointCloud2
            # display) — published only when a voxel mapper runs; the
            # subscription is inert otherwise.
            pub = n.create_publisher(sen.PointCloud2, "/voxel_points",
                                     self._ros_qos(depth=1))
            self._bus_to_ros("voxel_points", pub, self.voxel_points_to_ros)
        if "scan" in topics:
            for ns in self._robot_namespaces():
                bus_t = ns + self.BUS_TOPICS["scan"]
                pub = n.create_publisher(sen.LaserScan, "/" + bus_t,
                                         self._ros_qos(best_effort=True))
                self._bus_to_ros_raw(bus_t, pub, self.scan_to_ros)
        if "odom" in topics:
            for ns in self._robot_namespaces():
                bus_t = ns + self.BUS_TOPICS["odom"]
                pub = n.create_publisher(nav.Odometry, "/" + bus_t,
                                         self._ros_qos())
                self._bus_to_ros_raw(bus_t, pub, self.odom_to_ros)

    def _robot_namespaces(self):
        from jax_mapping.bridge.brain import robot_ns
        return [robot_ns(i, self.n_robots) for i in range(self.n_robots)]

    def _bus_to_ros(self, topic: str, ros_pub, convert) -> None:
        self._bus_to_ros_raw(self.BUS_TOPICS[topic], ros_pub, convert)

    def _bus_to_ros_raw(self, bus_topic: str, ros_pub, convert) -> None:
        def cb(msg, _pub=ros_pub, _cv=convert):
            out = _cv(msg)
            if out is not None:
                _pub.publish(out)
        self._subs.append(self.bus.subscribe(bus_topic, callback=cb))

    def _wire_inbound(self, topics) -> None:
        geo = self._msgs["geo"]
        sen = self._msgs["sen"]
        nav = self._msgs["nav"]
        n = self.node
        if "cmd_vel" in topics:
            pub = self.bus.publisher(self.BUS_TOPICS["cmd_vel"])
            n.create_subscription(
                geo.Twist, "/cmd_vel",
                lambda m, _p=pub: _p.publish(self.twist_from_ros(m)),
                self._ros_qos())
        if "scan" in topics:
            for ns in self._robot_namespaces():
                bus_t = ns + self.BUS_TOPICS["scan"]
                pub = self.bus.publisher(bus_t)
                n.create_subscription(
                    sen.LaserScan, "/" + bus_t,
                    lambda m, _p=pub: _p.publish(self.scan_from_ros(m)),
                    self._ros_qos(best_effort=True))
        if "odom" in topics:
            for ns in self._robot_namespaces():
                bus_t = ns + self.BUS_TOPICS["odom"]
                pub = self.bus.publisher(bus_t)
                n.create_subscription(
                    nav.Odometry, "/" + bus_t,
                    lambda m, _p=pub: _p.publish(self.odom_from_ros(m)),
                    self._ros_qos(depth=50))
        if "initialpose" in topics:
            # RViz's SetInitialPose tool (configs/jax_mapping.rviz, the
            # reference's rviz_config.rviz:186-198 carries the same tool):
            # relocalize the SLAM estimate (mapper consumes the bus topic).
            pub = self.bus.publisher(self.BUS_TOPICS["initialpose"])
            n.create_subscription(
                geo.PoseWithCovarianceStamped, "/initialpose",
                lambda m, _p=pub: _p.publish(self.pose_cov_from_ros(m)),
                self._ros_qos())
        if "goal_pose" in topics:
            # RViz's SetGoal tool; consumed by the brain + global
            # planner (the reference never launched a consumer — Nav2
            # was future work, report.pdf VI.2). /goal_pose addresses
            # robot 0; fleets also get /robotN/goal_pose so an operator
            # can direct ANY robot (brain's per-robot goal topics).
            pub = self.bus.publisher(self.BUS_TOPICS["goal_pose"])
            n.create_subscription(
                geo.PoseStamped, "/goal_pose",
                lambda m, _p=pub: _p.publish(self.pose_stamped_from_ros(m)),
                self._ros_qos())
            if self.n_robots > 1:
                for ns in self._robot_namespaces():
                    bus_t = ns + "goal_pose"
                    npub = self.bus.publisher(bus_t)
                    n.create_subscription(
                        geo.PoseStamped, "/" + bus_t,
                        lambda m, _p=npub: _p.publish(
                            self.pose_stamped_from_ros(m)),
                        self._ros_qos())

    def _wire_tf(self) -> None:
        import tf2_ros
        self._tf_bcast = tf2_ros.TransformBroadcaster(self.node)
        self.node.create_timer(self.cfg.tf_publish_period_s,
                               self.publish_tf_once)

    # -- conversions (field-for-field per the ROS interface definitions) ----

    def scan_to_ros(self, msg: LaserScan):
        sen, bi = self._msgs["sen"], self._msgs["bi"]
        out = sen.LaserScan()
        out.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        out.header.frame_id = msg.header.frame_id or "base_laser"
        out.angle_min = float(msg.angle_min)
        out.angle_max = float(msg.angle_max)
        out.angle_increment = float(msg.angle_increment)
        out.time_increment = float(msg.time_increment)
        out.scan_time = float(msg.scan_time)
        out.range_min = float(msg.range_min)
        out.range_max = float(msg.range_max)
        out.ranges = [float(r) for r in np.asarray(msg.ranges)]
        out.intensities = [float(v) for v in np.asarray(msg.intensities)]
        return out

    def scan_from_ros(self, m) -> LaserScan:
        return LaserScan(
            header=Header(stamp=_from_ros_time(m.header.stamp),
                          frame_id=m.header.frame_id),
            angle_min=float(m.angle_min), angle_max=float(m.angle_max),
            angle_increment=float(m.angle_increment),
            time_increment=float(m.time_increment),
            scan_time=float(m.scan_time),
            range_min=float(m.range_min), range_max=float(m.range_max),
            ranges=np.asarray(m.ranges, np.float32),
            intensities=np.asarray(m.intensities, np.float32),
        )

    def path_to_ros(self, msg):
        """Path -> nav_msgs/Path (PoseStamped per waypoint, identity
        orientation — the plan carries positions; heading comes from the
        brain's steering, not the path)."""
        nav, geo, bi = (self._msgs["nav"], self._msgs["geo"],
                        self._msgs["bi"])
        out = nav.Path()
        out.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        out.header.frame_id = msg.header.frame_id or "map"
        poses = []
        for x, y in np.asarray(msg.poses_xy, np.float32):
            ps = geo.PoseStamped()
            ps.header = out.header
            ps.pose.position.x = float(x)
            ps.pose.position.y = float(y)
            ps.pose.orientation.w = 1.0
            poses.append(ps)
        out.poses = poses
        return out

    def voxel_points_to_ros(self, msg):
        """VoxelPoints -> sensor_msgs/PointCloud2 (x/y/z float32, packed
        12-byte points) for the RViz PointCloud2 display."""
        sen, bi = self._msgs["sen"], self._msgs["bi"]
        pts = np.ascontiguousarray(np.asarray(msg.points, np.float32))
        out = sen.PointCloud2()
        out.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        out.header.frame_id = msg.header.frame_id or "map"
        out.height = 1
        out.width = int(pts.shape[0])
        fields = []
        for i, name in enumerate(("x", "y", "z")):
            f = sen.PointField()
            f.name = name
            f.offset = 4 * i
            f.datatype = 7                 # PointField.FLOAT32
            f.count = 1
            fields.append(f)
        out.fields = fields
        out.is_bigendian = False
        out.point_step = 12
        out.row_step = 12 * int(pts.shape[0])
        out.data = pts.tobytes()
        out.is_dense = True
        return out

    def occupancy_to_ros(self, msg: OccupancyGrid):
        nav, bi = self._msgs["nav"], self._msgs["bi"]
        out = nav.OccupancyGrid()
        out.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        out.header.frame_id = msg.header.frame_id or "map"
        out.info.resolution = float(msg.info.resolution)
        out.info.width = int(msg.info.width)
        out.info.height = int(msg.info.height)
        out.info.origin.position.x = float(msg.info.origin.x)
        out.info.origin.position.y = float(msg.info.origin.y)
        # Planar map: the origin rotation is pure yaw, so the quaternion's
        # x and y components are identically zero and only z/w are set.
        qx, qy, qz, qw = msg.info.origin.to_quaternion()
        out.info.origin.orientation.z = qz
        out.info.origin.orientation.w = qw
        out.data = [int(v) for v in np.asarray(msg.data, np.int8)]
        return out

    def odom_to_ros(self, msg: Odometry):
        nav, bi = self._msgs["nav"], self._msgs["bi"]
        out = nav.Odometry()
        out.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        out.header.frame_id = msg.header.frame_id or "odom"
        out.child_frame_id = msg.child_frame_id
        out.pose.pose.position.x = float(msg.pose.x)
        out.pose.pose.position.y = float(msg.pose.y)
        qx, qy, qz, qw = msg.pose.to_quaternion()
        out.pose.pose.orientation.z = qz
        out.pose.pose.orientation.w = qw
        out.twist.twist.linear.x = float(msg.twist.linear_x)
        out.twist.twist.angular.z = float(msg.twist.angular_z)
        return out

    def odom_from_ros(self, m) -> Odometry:
        from jax_mapping.bridge.messages import Pose2D
        yaw = _yaw_from_quat(m.pose.pose.orientation)
        return Odometry(
            header=Header(stamp=_from_ros_time(m.header.stamp),
                          frame_id=m.header.frame_id),
            child_frame_id=m.child_frame_id,
            pose=Pose2D(float(m.pose.pose.position.x),
                        float(m.pose.pose.position.y), yaw),
            twist=Twist(linear_x=float(m.twist.twist.linear.x),
                        angular_z=float(m.twist.twist.angular.z)),
        )

    def twist_from_ros(self, m) -> Twist:
        return Twist(linear_x=float(m.linear.x),
                     angular_z=float(m.angular.z))

    def pose_cov_from_ros(self, msg) -> "Pose2D":
        """geometry_msgs/PoseWithCovarianceStamped -> planar Pose2D."""
        from jax_mapping.bridge.messages import Pose2D
        p = msg.pose.pose
        return Pose2D(float(p.position.x), float(p.position.y),
                      _yaw_from_quat(p.orientation))

    def pose_stamped_from_ros(self, msg) -> "Pose2D":
        """geometry_msgs/PoseStamped -> planar Pose2D."""
        from jax_mapping.bridge.messages import Pose2D
        p = msg.pose
        return Pose2D(float(p.position.x), float(p.position.y),
                      _yaw_from_quat(p.orientation))

    def pose_list_to_ros(self, poses):
        """The Bus `/pose` payload is a list of per-robot pose dicts
        (bridge/mapper.py); ROS `/pose` is the FIRST robot's
        PoseWithCovarianceStamped (the reference is single-robot,
        rviz_config.rviz:133-143). The fleet view goes out as a
        PoseArray on `/poses` (see pose_list_to_ros_array)."""
        if not poses:
            return None
        geo, bi = self._msgs["geo"], self._msgs["bi"]
        p = poses[0]
        out = geo.PoseWithCovarianceStamped()
        out.header.stamp = _to_ros_time(bi.Time, p.get("stamp", 0.0))
        out.header.frame_id = "map"
        out.pose.pose.position.x = float(p["x"])
        out.pose.pose.position.y = float(p["y"])
        out.pose.pose.orientation.z = math.sin(p["theta"] / 2.0)
        out.pose.pose.orientation.w = math.cos(p["theta"] / 2.0)
        cov = p.get("cov")
        if cov is not None:
            # Row-major 6x6 (x y z r p y): the correlative matcher's
            # surface covariance (ops/scan_match MatchResult.cov) on the
            # x/x, y/y and yaw/yaw diagonals — what slam_toolbox's
            # PoseWithCovariance carries.
            c = [0.0] * 36
            c[0], c[7], c[35] = float(cov[0]), float(cov[1]), float(cov[2])
            out.pose.covariance = c
        return out

    def pose_list_to_ros_array(self, poses):
        """All robots' poses as one geometry_msgs/PoseArray (`/poses`)."""
        if not poses:
            return None
        geo, bi = self._msgs["geo"], self._msgs["bi"]
        out = geo.PoseArray()
        out.header.stamp = _to_ros_time(bi.Time, poses[0].get("stamp", 0.0))
        out.header.frame_id = "map"
        arr = []
        for p in poses:
            m = geo.Pose()
            m.position.x = float(p["x"])
            m.position.y = float(p["y"])
            m.orientation.z = math.sin(p["theta"] / 2.0)
            m.orientation.w = math.cos(p["theta"] / 2.0)
            arr.append(m)
        out.poses = arr
        return out

    def occupancy_to_ros_update(self, msg: OccupancyGrid):
        """Full-extent map_msgs/OccupancyGridUpdate (x=y=0, whole grid):
        the type RViz's Map display expects on its update topic."""
        mm = self._msgs["map_msgs"]
        bi = self._msgs["bi"]
        u = mm.OccupancyGridUpdate()
        u.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
        u.header.frame_id = msg.header.frame_id or "map"
        u.x = 0
        u.y = 0
        u.width = int(msg.info.width)
        u.height = int(msg.info.height)
        u.data = [int(v) for v in np.asarray(msg.data).ravel()]
        return u

    def frontiers_to_ros_markers(self, msg):
        """FrontierArray -> visualization_msgs/MarkerArray: one sphere per
        live cluster at its goal target, sized by cluster size, green when
        some robot claimed it, orange when unassigned. A DELETEALL leads
        so stale clusters vanish between updates."""
        vis = self._msgs["vis"]
        if vis is None:
            return None
        bi = self._msgs["bi"]
        out = vis.MarkerArray()
        clear = vis.Marker()
        clear.action = 3                      # DELETEALL
        markers = [clear]
        assigned = {int(a) for a in np.asarray(msg.assignment) if a >= 0}
        for k, (xy, size) in enumerate(zip(np.asarray(msg.targets_xy),
                                           np.asarray(msg.sizes))):
            if size <= 0:
                continue
            m = vis.Marker()
            m.header.stamp = _to_ros_time(bi.Time, msg.header.stamp)
            m.header.frame_id = "map"
            m.ns = "frontiers"
            m.id = k
            m.type = 2                        # SPHERE
            m.action = 0                      # ADD
            m.pose.position.x = float(xy[0])
            m.pose.position.y = float(xy[1])
            m.pose.orientation.w = 1.0
            s = 0.15 + 0.01 * min(float(size), 50.0)
            m.scale.x = m.scale.y = m.scale.z = s
            m.color.a = 0.9
            if k in assigned:
                m.color.g = 1.0
            else:
                m.color.r = 1.0
                m.color.g = 0.6
            markers.append(m)
        out.markers = markers
        return out

    def graph_to_ros_markers(self, msg):
        """GraphMarkers -> MarkerArray: one SPHERE_LIST per robot's nodes
        (color cycled per robot), one LINE_LIST of gray odometry edges,
        one LINE_LIST of red loop constraints. DELETEALL leads so a
        thinned/reset graph vanishes cleanly."""
        vis = self._msgs["vis"]
        if vis is None:
            return None
        bi = self._msgs["bi"]
        stamp = _to_ros_time(bi.Time, msg.header.stamp)

        def mk(ns, mid, mtype):
            m = vis.Marker()
            m.header.stamp = stamp
            m.header.frame_id = "map"
            m.ns = ns
            m.id = mid
            m.type = mtype
            m.action = 0
            m.pose.orientation.w = 1.0
            return m

        def pt(xy):
            g = self._msgs["geo"].Point()
            g.x, g.y, g.z = float(xy[0]), float(xy[1]), 0.02
            return g

        out = vis.MarkerArray()
        clear = vis.Marker()
        clear.action = 3                      # DELETEALL
        markers = [clear]
        nodes = np.asarray(msg.nodes_xy)
        nrob = np.asarray(msg.node_robot)
        palette = [(0.2, 0.6, 1.0), (1.0, 0.8, 0.2), (0.6, 1.0, 0.4),
                   (1.0, 0.4, 0.8)]
        for r in sorted(set(int(x) for x in nrob)):
            m = mk("graph_nodes", r, 7)       # SPHERE_LIST
            m.scale.x = m.scale.y = m.scale.z = 0.06
            cr, cg, cb = palette[r % len(palette)]
            m.color.r, m.color.g, m.color.b, m.color.a = cr, cg, cb, 0.9
            m.points = [pt(xy) for xy in nodes[nrob == r]]
            markers.append(m)
        edges = np.asarray(msg.edges_xy)
        isloop = np.asarray(msg.edge_is_loop)
        for name, mid, sel, col in (
                ("graph_edges", 0, ~isloop, (0.6, 0.6, 0.6)),
                ("graph_loops", 1, isloop, (1.0, 0.2, 0.2))):
            m = mk(name, mid, 5)              # LINE_LIST
            m.scale.x = 0.015
            m.color.r, m.color.g, m.color.b = col
            m.color.a = 0.8
            pts = []
            for e in edges[sel] if len(edges) else []:
                pts += [pt(e[0]), pt(e[1])]
            m.points = pts
            markers.append(m)
        out.markers = markers
        return out

    def publish_tf_once(self) -> None:
        """Broadcast every transform currently in the TfTree."""
        geo, bi = self._msgs["geo"], self._msgs["bi"]
        out = []
        for t in self.tf.all_transforms():
            m = geo.TransformStamped()
            m.header.stamp = _to_ros_time(bi.Time, t.header.stamp)
            m.header.frame_id = t.header.frame_id
            m.child_frame_id = t.child_frame_id
            m.transform.translation.x = float(t.x)
            m.transform.translation.y = float(t.y)
            m.transform.translation.z = float(t.z)
            m.transform.rotation.z = math.sin(t.theta / 2.0)
            m.transform.rotation.w = math.cos(t.theta / 2.0)
            out.append(m)
        if out:
            self._tf_bcast.sendTransform(out)

    # -- lifecycle ----------------------------------------------------------

    def spin(self) -> None:
        """rclpy.spin in a daemon thread (the reference's pattern,
        `server/.../main.py:285-286`)."""
        import rclpy

        def run():
            while not self._shutdown.is_set() and rclpy.ok():
                rclpy.spin_once(self.node, timeout_sec=0.1)

        self._spin_thread = threading.Thread(target=run, daemon=True)
        self._spin_thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._spin_thread is not None:
            self._spin_thread.join(timeout=2.0)
        for s in self._subs:
            s.close()
        try:
            self.node.destroy_node()
        except Exception:
            pass
