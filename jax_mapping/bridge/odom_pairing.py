"""Drop/reorder-tolerant odometry pairing, shared by the 2D and 3D
mappers.

Best-Effort sensor delivery (report.pdf §V.A) means scans/depth images
and odometry arrive dropped and reordered; each sensor sample pairs with
the FRESHEST odometry at or before its stamp. One implementation so a
pairing-rule fix cannot silently apply to one mapper and not the other
(the duplication code review flagged in round 4).
"""

from __future__ import annotations

from typing import List, Optional

from jax_mapping.bridge.messages import Odometry


class OdomPairer:
    """Per-robot bounded odometry history + stamp pairing.

    Not internally locked: both mapper nodes already serialize access
    under their own state locks (bus callbacks and tick share the node's
    lock), and locking twice per message would buy nothing.
    """

    def __init__(self, n_robots: int, max_hist: int = 200):
        self._hist: List[List[Odometry]] = [[] for _ in range(n_robots)]
        self._max = max_hist

    def push(self, i: int, od: Odometry) -> None:
        hist = self._hist[i]
        hist.append(od)
        if len(hist) > self._max:
            del hist[: self._max // 2]

    def pair(self, i: int, stamp: float) -> Optional[Odometry]:
        """Freshest odometry at or before `stamp`; the oldest sample when
        the scan predates all odometry (bootstrap); None when no odometry
        has arrived at all."""
        best = None
        for od in self._hist[i]:
            if od.header.stamp <= stamp and \
                    (best is None or od.header.stamp > best.header.stamp):
                best = od
        if best is None and self._hist[i]:
            # Bootstrap: the scan predates all odometry. hist[0] is only
            # the first-ARRIVED sample; under the reordered delivery this
            # module exists to tolerate, a later-arriving older sample is
            # the better anchor — pick by stamp, not arrival order.
            best = min(self._hist[i], key=lambda od: od.header.stamp)
        return best
