"""Node + executor model mirroring the rclpy surface the reference uses.

The reference's nodes are rclpy Nodes with `create_publisher`,
`create_subscription`, `create_timer`, spun on a daemon thread while Flask
owns the main thread (`/root/reference/server/thymio_project/thymio_project/
main.py:39-60,281-289`). This module provides the same construction surface
against the in-process Bus, with an explicit executor whose callbacks are
serialized per-node (rclpy's default single-threaded executor semantics) —
removing the reference's reliance on the GIL for safety (SURVEY.md §5).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, List, Optional

from jax_mapping.bridge.bus import Bus, Publisher, Subscription
from jax_mapping.bridge.qos import QoSProfile, qos_default
from jax_mapping.bridge.tf import TfTree


class Timer:
    def __init__(self, period_s: float, callback: Callable[[], None]):
        self.period_s = period_s
        self.callback = callback
        self.next_due = time.monotonic() + period_s
        self.cancelled = False
        self.n_calls = 0

    def cancel(self) -> None:
        self.cancelled = True


class Node:
    """Base class for framework nodes; subclasses add callbacks."""

    def __init__(self, name: str, bus: Bus, tf: Optional[TfTree] = None):
        self.name = name
        self.bus = bus
        self.tf = tf if tf is not None else TfTree()
        self._timers: List[Timer] = []
        self._subs: List[Subscription] = []
        # Reentrant: inline bus delivery means a guarded callback that
        # publishes can re-enter this node's guard on the same thread.
        self._cb_lock = threading.RLock()
        self.n_errors = 0

    # rclpy-shaped construction surface ------------------------------------

    def create_publisher(self, topic: str,
                         qos: QoSProfile = qos_default) -> Publisher:
        return self.bus.publisher(topic, qos)

    def create_subscription(self, topic: str,
                            callback: Callable[[Any], None],
                            qos: QoSProfile = qos_default) -> Subscription:
        sub = self.bus.subscribe(topic, qos,
                                 callback=self._guarded(callback))
        self._subs.append(sub)
        return sub

    def create_timer(self, period_s: float,
                     callback: Callable[[], None]) -> Timer:
        timer = Timer(period_s, self._guarded(callback))
        self._timers.append(timer)
        return timer

    def destroy(self) -> None:
        for t in self._timers:
            t.cancel()
        for s in self._subs:
            s.close()

    # ----------------------------------------------------------------------

    def _guarded(self, fn: Callable) -> Callable:
        """Serialize callbacks and contain exceptions (the reference's
        catch-all that drops the Thymio connection rather than crashing the
        loop, `server/.../main.py:198-200`)."""
        def wrapper(*a, **kw):
            with self._cb_lock:
                try:
                    return fn(*a, **kw)
                except Exception:
                    self.n_errors += 1
                    traceback.print_exc()
        return wrapper


class Executor:
    """Timer scheduler for a set of nodes.

    Subscriptions with callbacks fire on the publisher's thread (the bus
    delivers inline, like rmw listener threads); timers fire here. `spin()`
    blocks; `spin_thread()` is the reference's daemon-thread pattern
    (`server/.../main.py:285-286`).
    """

    def __init__(self, nodes: Optional[List[Node]] = None):
        self.nodes: List[Node] = list(nodes or [])
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_node(self, node: Node) -> None:
        self.nodes.append(node)

    def spin(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            due: List[Timer] = []
            soonest = now + 0.05
            for node in self.nodes:
                for t in node._timers:
                    if t.cancelled:
                        continue
                    if t.next_due <= now:
                        due.append(t)
                        # Fixed-rate schedule; skip missed periods rather
                        # than bursting to catch up.
                        periods = int((now - t.next_due) / t.period_s) + 1
                        t.next_due += periods * t.period_s
                    soonest = min(soonest, t.next_due)
            for t in due:
                t.n_calls += 1
                t.callback()
            wait = max(soonest - time.monotonic(), 0.0)
            if wait > 0:
                self._stop.wait(timeout=wait)

    def spin_thread(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.spin, daemon=True,
                                        name="executor-spin")
        self._thread.start()
        return self._thread

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for node in self.nodes:
            node.destroy()
