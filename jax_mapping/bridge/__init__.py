"""ROS-shaped plugin boundary.

The reference keeps slam_toolbox, RViz, and Nav2 working by speaking standard
ROS 2 messages over DDS (SURVEY.md §1 LX, §2.2). This package provides that
boundary for the TPU framework: message dataclasses mirroring the ROS 2 wire
types, an in-process pub/sub bus with DDS-like QoS semantics (Best-Effort
drops included), a TF tree, and a Node/executor model — so the node graph
shape of the reference (`/scan` + `/odom` in, `/map` + `/frontiers` out) is
preserved exactly, and a thin rclpy adapter can swap the bus for real DDS
when ROS 2 is present.
"""

from jax_mapping.bridge.messages import (  # noqa: F401
    FrontierArray, Header, LaserScan, MapMetaData, OccupancyGrid, Odometry,
    Pose2D, TransformStamped, Twist,
)
from jax_mapping.bridge.qos import QoSProfile, Reliability  # noqa: F401
from jax_mapping.bridge.bus import Bus  # noqa: F401
from jax_mapping.bridge.node import Node, Executor  # noqa: F401
from jax_mapping.bridge.tf import TfTree  # noqa: F401

# Heavier pieces (driver, brain, mapper, sim_node, http_api, launch) are
# imported from their modules directly; they pull in jax at import time.
