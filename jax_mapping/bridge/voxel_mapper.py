"""Voxel mapper node: depth images -> the fleet's shared 3D log-odds map.

The 3D counterpart of `bridge/mapper.py` in the node graph (BASELINE
configs[4]): subscribes `{ns}depth` (Best-Effort sensor QoS) + `{ns}odom`
per robot, pairs each depth image with the freshest odometry at or before
its stamp (the 2D mapper's drop/reorder-tolerant batcher), and fuses
batches on device through `ops.voxel.fuse_depths` into ONE shared voxel
grid for the whole fleet — the same single-map memory architecture as the
2D mapper.

Pose source is odometry, not SLAM: depth fusion rides on the 2D
pipeline's pose estimates in a full deployment (the mapper's `map->odom`
correction applies upstream); standalone it maps in the odom frame. The
camera mount (height, pitch) comes from DepthCamConfig.

Exports mirror the 2D mapper's: `voxel_grid()` (log-odds), plus the 2.5D
projections a planner or UI consumes — `height_map()` and
`obstacle_slice()` — and a grayscale height-map image with the /map-image
color convention's spirit (0 = unknown column, brighter = taller).
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional

import numpy as np

from jax_mapping.bridge.brain import robot_ns
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import DepthImage, Odometry
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.odom_pairing import OdomPairer
from jax_mapping.bridge.qos import QoSProfile, qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig
from jax_mapping.utils import global_metrics as M


class VoxelMapperNode(Node):
    """Device-resident 3D mapping behind the topic contract."""

    def __init__(self, cfg: SlamConfig, bus: Bus,
                 tf: Optional[TfTree] = None, n_robots: int = 1,
                 tick_period_s: Optional[float] = None):
        super().__init__("jax_voxel_mapper", bus, tf)
        import jax.numpy as jnp

        from jax_mapping.ops import voxel as V

        self.cfg = cfg
        self.n_robots = n_robots
        self._V, self._jnp = V, jnp
        V._check_patch_coverage(cfg.voxel, cfg.depthcam)

        self._lock = threading.Lock()
        self.grid = V.empty_voxel_grid(cfg.voxel)
        self._depth_q: List[List[DepthImage]] = [[] for _ in range(n_robots)]
        self._pairer = OdomPairer(n_robots)
        self.n_images_fused = 0
        self.n_images_dropped_unpaired = 0
        #: Bumped on out-of-band grid replacement (restore_grid); cache
        #: keys combine it with n_images_fused.
        self.map_revision = 0

        for i in range(n_robots):
            ns = robot_ns(i, n_robots)
            self.create_subscription(
                f"{ns}depth", functools.partial(self._depth_cb, i),
                qos_sensor_data)
            self.create_subscription(
                f"{ns}odom", functools.partial(self._odom_cb, i),
                QoSProfile(depth=50))

        self.points_pub = self.create_publisher("/voxel_points")
        #: Point export cap: a fully-mapped production grid can hold
        #: millions of occupied voxels; RViz chokes long before that.
        self.max_points = 65536

        period = tick_period_s if tick_period_s is not None \
            else 1.0 / cfg.robot.control_rate_hz
        self.create_timer(period, self.tick)
        self.create_timer(cfg.map_publish_period_s, self.publish_points)

    # -- callbacks ----------------------------------------------------------

    def _depth_cb(self, i: int, msg: DepthImage) -> None:
        with self._lock:
            self._depth_q[i].append(msg)

    def _odom_cb(self, i: int, msg: Odometry) -> None:
        with self._lock:
            self._pairer.push(i, msg)

    # -- device step --------------------------------------------------------

    def tick(self) -> None:
        """Drain queues, fuse each robot's batch on device."""
        jnp = self._jnp
        cam = self.cfg.depthcam
        with self._lock:
            work = []
            for i in range(self.n_robots):
                for msg in sorted(self._depth_q[i],
                                  key=lambda m: m.header.stamp):
                    od = self._pairer.pair(i, msg.header.stamp)
                    if od is None:
                        self.n_images_dropped_unpaired += 1
                        M.counters.inc("voxel_mapper.images_unpaired")
                        continue
                    if msg.depth.shape != (cam.height_px, cam.width_px):
                        # Shape drift would silently mis-project through
                        # the pinhole model; refuse loudly in counters.
                        M.counters.inc("voxel_mapper.images_bad_shape")
                        continue
                    work.append((msg.depth, od.pose))
                self._depth_q[i].clear()
        if not work:
            return
        depths = np.stack([w[0] for w in work]).astype(np.float32)
        poses = np.asarray([[w[1].x, w[1].y, w[1].theta] for w in work],
                           np.float32)
        with M.stages.stage("voxel_mapper.fuse"):
            with self._lock:
                base_grid = self.grid
                base_revision = self.map_revision
            grid = self._V.fuse_depths(self.cfg.voxel, cam, base_grid,
                                       jnp.asarray(depths),
                                       jnp.asarray(poses))
            with self._lock:
                # Same stale-state guard as mapper._finish_step: a
                # restore_grid (HTTP /load, demo --resume) landing while
                # we fused would be silently overwritten by a grid fused
                # from the pre-restore state. Drop the fused result; the
                # images are lost, the restored map is not.
                if self.map_revision != base_revision \
                        or self.grid is not base_grid:
                    M.counters.inc("voxel_mapper.fuse_dropped_stale")
                    return
                self.grid = grid
        self.n_images_fused += len(work)
        M.counters.inc("voxel_mapper.images_fused", len(work))

    # -- exports ------------------------------------------------------------

    def voxel_grid(self):
        with self._lock:
            return self.grid

    def height_map(self) -> np.ndarray:
        return np.asarray(self._V.height_map(self.cfg.voxel,
                                             self.voxel_grid()))

    def obstacle_slice(self, z_min_m: float, z_max_m: float) -> np.ndarray:
        return np.asarray(self._V.obstacle_slice(
            self.cfg.voxel, self.voxel_grid(), z_min_m, z_max_m))

    # -- checkpoint surface -------------------------------------------------

    def snapshot_grid(self):
        """The 3D map state for checkpoints (the grid IS the whole
        device state; counters are telemetry)."""
        return self.voxel_grid()

    def restore_grid(self, grid) -> None:
        g = self._jnp.asarray(grid)
        want = (self.cfg.voxel.size_z_cells, self.cfg.voxel.size_y_cells,
                self.cfg.voxel.size_x_cells)
        if g.shape != want:
            raise ValueError(
                f"voxel checkpoint shape {g.shape} != configured {want}")
        with self._lock:
            self.grid = g
            # Content changed without fusing: consumers keying caches on
            # n_images_fused must see a new revision or serve stale data.
            self.map_revision += 1

    def publish_points(self) -> None:
        """Occupied-voxel centres on `/voxel_points` (uniformly subsampled
        past `max_points`), the 3D analog of the mapper's /map publish."""
        from jax_mapping.bridge.messages import Header, VoxelPoints
        pts = self._V.occupied_voxel_centers(self.cfg.voxel,
                                             self.voxel_grid())
        if len(pts) > self.max_points:
            idx = np.linspace(0, len(pts) - 1, self.max_points) \
                .round().astype(int)
            pts = pts[idx]
        self.points_pub.publish(VoxelPoints(header=Header.now("map"),
                                            points=pts))

    def height_map_image(self) -> np.ndarray:
        """(Y, X) uint8 grayscale: 0 = no occupied voxel in the column,
        1..255 scale linearly with top-surface height over the grid's z
        extent; flipud for image coords (the /map-image convention)."""
        hm = self.height_map()
        _, _, ez = self.cfg.voxel.extent_m
        img = np.zeros(hm.shape, np.uint8)
        mapped = hm >= 0.0
        img[mapped] = (1.0 + 254.0 * np.clip(hm[mapped] / ez, 0.0, 1.0)) \
            .astype(np.uint8)
        return np.flipud(img)
