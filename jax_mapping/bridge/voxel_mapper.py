"""Voxel mapper node: depth images -> the fleet's shared 3D log-odds map.

The 3D counterpart of `bridge/mapper.py` in the node graph (BASELINE
configs[4]): subscribes `{ns}depth` (Best-Effort sensor QoS) + `{ns}odom`
per robot, pairs each depth image with the freshest odometry at or before
its stamp (the 2D mapper's drop/reorder-tolerant batcher), and fuses
batches on device through `ops.voxel.fuse_depths` into ONE shared voxel
grid for the whole fleet — the same single-map memory architecture as the
2D mapper.

SLAM coupling (round-5; the round-4 node fused at raw odometry and kept
its drift ghosts forever, unlike slam_toolbox's fully-corrected single
map, slam_config.yaml:43-48):

* Every image fuses at the CORRECTED pose: the 2D mapper's live map->odom
  correction (`mapper.depth_anchor`) applied to the image's paired
  odometry, so the 3D map lives in the map frame, not the odom frame.
* A bounded depth-keyframe ring (VoxelConfig.keyframe_cap) mirrors the 2D
  scan ring: a keyframe is stored when the robot has moved past the 2D
  key-scan gate (matcher.min_travel_m / min_heading_rad), anchored to the
  robot's current GRAPH node as a relative pose — so optimizing the graph
  moves the keyframe with its node, exactly slam_toolbox's scan-holding
  semantics.
* After a loop closure the voxel grid is RE-FUSED from the keyframe ring
  at the optimized node poses (`_refuse_from_keyframes`) — the 3D analog
  of the 2D mapper's ring re-fusion — so 3D walls de-ghost when the 2D
  map does. Non-keyframe images fused since the last closure contribute
  only until the next re-fuse, the same lifetime non-key scans have in
  2D. Graph thinning (ops/posegraph.thin_keyframes) halves node indices;
  keyframes carry their capture-time thin count and re-anchor through
  `idx >> (thins_now - thins_then)` — the even-node-at-or-before
  approximation thinning itself uses for surviving edges.

Standalone (mapper=None) the node still maps in the odom frame.  The
camera mount (height, pitch) comes from DepthCamConfig.

Exports mirror the 2D mapper's: `voxel_grid()` (log-odds), plus the 2.5D
projections a planner or UI consumes — `height_map()` and
`obstacle_slice()` — and a grayscale height-map image with the /map-image
color convention's spirit (0 = unknown column, brighter = taller).
"""

from __future__ import annotations

import functools
import math
import threading
from typing import List, Optional

import numpy as np

from jax_mapping.bridge.brain import robot_ns
from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import DepthImage, Odometry
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.odom_pairing import OdomPairer
from jax_mapping.bridge.qos import QoSProfile, qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig
from jax_mapping.utils import global_metrics as M


# Host-side SE(2) mirrors of ops/odometry.pose_compose/pose_between: the
# per-image correction math runs on 3-vectors where a device round trip
# per image would dominate the tick.

def _se2_compose(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    ca, sa = math.cos(a[2]), math.sin(a[2])
    return np.array([a[0] + ca * b[0] - sa * b[1],
                     a[1] + sa * b[0] + ca * b[1],
                     a[2] + b[2]], np.float32)


def _se2_between(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    ca, sa = math.cos(a[2]), math.sin(a[2])
    dx, dy = b[0] - a[0], b[1] - a[1]
    dth = (b[2] - a[2] + math.pi) % (2.0 * math.pi) - math.pi
    return np.array([ca * dx + sa * dy, -sa * dx + ca * dy, dth],
                    np.float32)


class _Keyframe:
    """One depth keyframe, anchored to a 2D graph node."""

    __slots__ = ("depth", "rel", "node_idx", "thins", "gen")

    def __init__(self, depth, rel, node_idx, thins, gen):
        self.depth = depth          # (H, W) np.float32
        self.rel = rel              # (3,) pose in the anchor node's frame
        self.node_idx = node_idx    # graph node index at capture
        self.thins = thins          # graph thin count at capture
        self.gen = gen              # mapper state generation at capture


class _ThinSim:
    """Per-robot replica of the 2D graph's thinning schedule.

    `models/slam.key_branch` thins exactly when a key add finds the ring
    full (n >= cap -> n = (cap+1)//2), so the thin count after k key
    scans is a deterministic function of k; advancing this mirror to the
    mapper's n_keyscans counter tells the keyframe ring how many times
    node indices have halved since each capture."""

    __slots__ = ("cap", "k", "n", "t")

    def __init__(self, cap: int):
        self.cap, self.k, self.n, self.t = cap, 0, 0, 0

    def thins_at(self, k: int) -> int:
        if k < self.k:              # fresh chain (/initialpose, restore)
            self.k, self.n, self.t = 0, 0, 0
        while self.k < k:
            if self.n >= self.cap:
                self.n = (self.cap + 1) // 2
                self.t += 1
            self.n += 1
            self.k += 1
        return self.t


class VoxelMapperNode(Node):
    """Device-resident 3D mapping behind the topic contract."""

    def __init__(self, cfg: SlamConfig, bus: Bus,
                 tf: Optional[TfTree] = None, n_robots: int = 1,
                 tick_period_s: Optional[float] = None, mapper=None):
        super().__init__("jax_voxel_mapper", bus, tf)
        import jax.numpy as jnp

        from jax_mapping.ops import voxel as V

        self.cfg = cfg
        self.n_robots = n_robots
        self._V, self._jnp = V, jnp
        V._check_patch_coverage(cfg.voxel, cfg.depthcam)

        #: The 2D MapperNode whose corrections/graph this node follows;
        #: None = standalone odom-frame mapping.
        self.mapper = mapper

        self._lock = threading.Lock()
        self.grid = V.empty_voxel_grid(cfg.voxel)
        self._depth_q: List[List[DepthImage]] = [[] for _ in range(n_robots)]
        self._pairer = OdomPairer(n_robots)
        self.n_images_fused = 0
        self.n_images_dropped_unpaired = 0
        #: Bumped on out-of-band grid replacement (restore_grid, closure
        #: re-fuse); cache keys combine it with n_images_fused.
        self.map_revision = 0

        # SLAM-coupled state (all under self._lock).
        self._keyframes: List[List[_Keyframe]] = \
            [[] for _ in range(n_robots)]
        self._last_kf_pose: List[Optional[np.ndarray]] = [None] * n_robots
        self._thin_sim = [_ThinSim(cfg.loop.max_poses)
                          for _ in range(n_robots)]
        self._loops_seen = 0
        self.n_keyframes_stored = 0
        self.n_refuses = 0

        for i in range(n_robots):
            ns = robot_ns(i, n_robots)
            self.create_subscription(
                f"{ns}depth", functools.partial(self._depth_cb, i),
                qos_sensor_data)
            self.create_subscription(
                f"{ns}odom", functools.partial(self._odom_cb, i),
                QoSProfile(depth=50))

        self.points_pub = self.create_publisher("/voxel_points")
        #: Point export cap: a fully-mapped production grid can hold
        #: millions of occupied voxels; RViz chokes long before that.
        self.max_points = 65536

        period = tick_period_s if tick_period_s is not None \
            else 1.0 / cfg.robot.control_rate_hz
        self.create_timer(period, self.tick)
        self.create_timer(cfg.map_publish_period_s, self.publish_points)

    # -- callbacks ----------------------------------------------------------

    def _depth_cb(self, i: int, msg: DepthImage) -> None:
        with self._lock:
            self._depth_q[i].append(msg)

    def _odom_cb(self, i: int, msg: Odometry) -> None:
        with self._lock:
            self._pairer.push(i, msg)

    # -- SLAM coupling ------------------------------------------------------

    def _corrected_pose(self, anchor, od_pose: np.ndarray) -> np.ndarray:
        """Corrected world pose for an image paired with od_pose; anchor
        from mapper.depth_anchor (None = uncorrected: standalone mode or
        before the 2D mapper's first step)."""
        if anchor is None:
            return od_pose
        _, est, odom_then, _, _, _ = anchor
        # T_map_odom = est ∘ odom_then^-1 applied to the capture odom.
        return _se2_compose(est, _se2_between(odom_then, od_pose))

    def _maybe_keyframe(self, i: int, depth: np.ndarray,
                        corrected: np.ndarray, anchor) -> None:
        """Store a depth keyframe when the robot moved past the 2D
        key-scan gate; caller holds no lock (list append under lock)."""
        if anchor is None or anchor[3] < 0:
            # No graph node to anchor to (localization mode: frozen map,
            # no graph, no closures — keyframes would never re-fuse).
            return
        m = self.cfg.matcher
        last = self._last_kf_pose[i]
        if last is not None:
            d = math.hypot(corrected[0] - last[0], corrected[1] - last[1])
            dth = abs((corrected[2] - last[2] + math.pi)
                      % (2.0 * math.pi) - math.pi)
            if d <= m.min_travel_m and dth <= m.min_heading_rad:
                return
        gen, _, _, node_idx, node_pose, k_then = anchor
        kf = _Keyframe(depth=np.array(depth, np.float32, copy=True),
                       rel=_se2_between(node_pose, corrected),
                       node_idx=node_idx,
                       thins=self._thin_sim[i].thins_at(k_then),
                       gen=gen)
        with self._lock:
            ring = self._keyframes[i]
            ring.append(kf)
            # keyframe_cap is a PER-FLEET memory bound (config.py): each
            # robot's ring gets an equal share of the slots.
            if len(ring) > max(1, self.cfg.voxel.keyframe_cap
                               // self.n_robots):
                # Ring full: halve keyframe density, even decimation
                # (the thin_keyframes longevity pattern).
                self._keyframes[i] = ring[::2]
                M.counters.inc("voxel_mapper.keyframe_thins")
        self._last_kf_pose[i] = corrected
        self.n_keyframes_stored += 1
        M.counters.inc("voxel_mapper.keyframes")

    def _refuse_from_keyframes(self) -> None:
        """Rebuild the voxel grid from the keyframe ring at the OPTIMIZED
        graph poses — the 3D analog of the 2D ring re-fusion after a loop
        closure. Keyframes from a stale state generation (a chain reset
        since capture) are dropped; keyframes whose anchor node thinned
        away re-anchor to the surviving even node at-or-before."""
        jnp = self._jnp
        depths, poses = [], []
        for i in range(self.n_robots):
            gen, node_poses, node_valid, n_now, k_now = \
                self.mapper.graph_snapshot(i)
            t_now = self._thin_sim[i].thins_at(k_now)
            with self._lock:
                keep = [kf for kf in self._keyframes[i] if kf.gen == gen]
                self._keyframes[i] = keep
                ring = list(keep)
            for kf in ring:
                idx = kf.node_idx >> (t_now - kf.thins)
                if idx >= n_now or not bool(node_valid[idx]):
                    continue
                depths.append(kf.depth)
                poses.append(_se2_compose(node_poses[idx], kf.rel))
        if not depths:
            return
        with self._lock:
            base_revision = self.map_revision
        with M.stages.stage("voxel_mapper.refuse"):
            grid = self._V.fuse_depths(
                self.cfg.voxel, self.cfg.depthcam,
                self._V.empty_voxel_grid(self.cfg.voxel),
                jnp.asarray(np.stack(depths)),
                jnp.asarray(np.stack(poses, dtype=np.float32)))
            with self._lock:
                if self.map_revision != base_revision:
                    M.counters.inc("voxel_mapper.fuse_dropped_stale")
                    return
                self.grid = grid
                # Content replaced out-of-band of n_images_fused: bump so
                # PNG caches keyed on (fused, revision) refresh.
                self.map_revision += 1
        self.n_refuses += 1
        M.counters.inc("voxel_mapper.refuses", 1)

    # -- device step --------------------------------------------------------

    def tick(self) -> None:
        """Drain queues, fuse each robot's batch on device at corrected
        poses; re-fuse from keyframes when the 2D mapper closed a loop."""
        jnp = self._jnp
        cam = self.cfg.depthcam
        with self._lock:
            work = []
            for i in range(self.n_robots):
                for msg in sorted(self._depth_q[i],
                                  key=lambda m: m.header.stamp):
                    od = self._pairer.pair(i, msg.header.stamp)
                    if od is None:
                        self.n_images_dropped_unpaired += 1
                        M.counters.inc("voxel_mapper.images_unpaired")
                        continue
                    if msg.depth.shape != (cam.height_px, cam.width_px):
                        # Shape drift would silently mis-project through
                        # the pinhole model; refuse loudly in counters.
                        M.counters.inc("voxel_mapper.images_bad_shape")
                        continue
                    work.append((i, msg.depth, od.pose))
                self._depth_q[i].clear()
        if work:
            # One anchor snapshot per robot per tick: the correction
            # basis moves at the 2D mapper's step cadence, not per image.
            anchors = {}
            for i in {i for i, _, _ in work}:
                anchors[i] = self.mapper.depth_anchor(i) \
                    if self.mapper is not None else None
            depths, poses = [], []
            for i, depth, od_pose in work:
                od_np = np.array([od_pose.x, od_pose.y, od_pose.theta],
                                 np.float32)
                corrected = self._corrected_pose(anchors[i], od_np)
                depths.append(depth)
                poses.append(corrected)
                self._maybe_keyframe(i, depth, corrected, anchors[i])
            depths = np.stack(depths).astype(np.float32)
            poses = np.stack(poses).astype(np.float32)
            with M.stages.stage("voxel_mapper.fuse"):
                with self._lock:
                    base_grid = self.grid
                    base_revision = self.map_revision
                grid = self._V.fuse_depths(self.cfg.voxel, cam, base_grid,
                                           jnp.asarray(depths),
                                           jnp.asarray(poses))
                with self._lock:
                    # Same stale-state guard as mapper._finish_step: a
                    # restore_grid (HTTP /load, demo --resume) landing
                    # while we fused would be silently overwritten by a
                    # grid fused from the pre-restore state. Drop the
                    # fused result; the images are lost, the restored
                    # map is not.
                    if self.map_revision != base_revision \
                            or self.grid is not base_grid:
                        M.counters.inc("voxel_mapper.fuse_dropped_stale")
                        grid = None
                    else:
                        self.grid = grid
            if grid is not None:
                self.n_images_fused += len(work)
                M.counters.inc("voxel_mapper.images_fused", len(work))
        if self.mapper is not None:
            loops = self.mapper.n_loops_closed
            if loops != self._loops_seen:
                self._loops_seen = loops
                self._refuse_from_keyframes()

    # -- exports ------------------------------------------------------------

    def voxel_grid(self):
        with self._lock:
            return self.grid

    def height_map(self) -> np.ndarray:
        # np.array, not np.asarray: asarray of a device array is a
        # zero-copy READ-ONLY view (lint C3), and this is the public
        # 2.5D export — consumers masking/annotating it in place would
        # hit "assignment destination is read-only" on their first
        # write (or worse, alias the live device buffer).
        return np.array(self._V.height_map(self.cfg.voxel,
                                           self.voxel_grid()))

    def obstacle_slice(self, z_min_m: float, z_max_m: float) -> np.ndarray:
        # Writable copy for the same C3 reason as height_map.
        return np.array(self._V.obstacle_slice(
            self.cfg.voxel, self.voxel_grid(), z_min_m, z_max_m))

    # -- serving surface (serving/tiles.py) ----------------------------------

    def serving_revision(self) -> int:
        """Monotonic content revision for the tile store: every grid
        change bumps exactly one of the two nondecreasing counters the
        PNG cache already keys on (`n_images_fused` for fusions,
        `map_revision` for out-of-band replacements), so their sum
        strictly increases per change. Lock-free read, the /status
        counter convention."""
        return self.n_images_fused + self.map_revision

    def serving_snapshot(self):
        """(revision, height-map uint8 image in GRID orientation) — the
        voxel height-map tiles ride the same TileStore as the 2D map,
        so this is `height_map_image` WITHOUT the flipud (tiles compose
        in grid coordinates; clients flip once for display). The 3D
        mapper has no patch-extent dirty marks — the store's on-device
        hash diff alone decides which tiles re-encode.

        Revision is read BEFORE the grid snapshot (counter reads stay
        lock-free, the /status convention): a fusion landing between
        the two leaves newer content under an older stamp, which the
        next freshness peek heals by re-refreshing — the reverse order
        would stamp OLD content with the new revision and serve it as
        current forever."""
        rev = self.n_images_fused + self.map_revision
        grid = self.voxel_grid()
        hm = np.asarray(self._V.height_map(self.cfg.voxel, grid))
        return rev, self._height_to_gray(hm)

    def _height_to_gray(self, hm: np.ndarray) -> np.ndarray:
        """THE height-to-grayscale palette: 0 = no occupied voxel in
        the column, 1..255 linear in top-surface height over the z
        extent — shared by /voxel-image and the tile store so the two
        renderings of one map can never diverge."""
        _, _, ez = self.cfg.voxel.extent_m
        img = np.zeros(hm.shape, np.uint8)
        mapped = hm >= 0.0
        img[mapped] = (1.0 + 254.0 * np.clip(hm[mapped] / ez, 0.0, 1.0)) \
            .astype(np.uint8)
        return img

    # -- checkpoint surface -------------------------------------------------

    def snapshot_grid(self):
        """The 3D map state for checkpoints (the grid IS the whole
        device state; counters are telemetry)."""
        return self.voxel_grid()

    def snapshot_keyframes(self) -> dict:
        """The depth-keyframe ring as flat arrays for the .voxelkf
        checkpoint sidecar (io/checkpoint.save_keyframe_sidecar). State
        generations are process-local and deliberately NOT serialized —
        restore_keyframes re-tags with the live generation."""
        with self._lock:
            kfs = [(i, kf) for i in range(self.n_robots)
                   for kf in self._keyframes[i]]
        H, W = self.cfg.depthcam.height_px, self.cfg.depthcam.width_px
        return {
            "depths": (np.stack([kf.depth for _, kf in kfs])
                       if kfs else np.zeros((0, H, W), np.float32)),
            "rels": np.asarray([kf.rel for _, kf in kfs],
                               np.float32).reshape(len(kfs), 3),
            "node_idx": np.asarray([kf.node_idx for _, kf in kfs],
                                   np.int32),
            "thins": np.asarray([kf.thins for _, kf in kfs], np.int32),
            "robot": np.asarray([i for i, _ in kfs], np.int32),
        }

    def validate_keyframes(self, kf: dict) -> None:
        """Raise ValueError if a keyframe sidecar cannot be restored into
        THIS node (shape/robot-range drift). Split out so /load can
        validate BEFORE any restore mutates live state (the handler's
        409-with-everything-untouched contract)."""
        H, W = self.cfg.depthcam.height_px, self.cfg.depthcam.width_px
        depths = np.asarray(kf["depths"], np.float32)
        if depths.ndim != 3 or depths.shape[1:] != (H, W):
            raise ValueError(
                f"keyframe depths shape {depths.shape} != (K, {H}, {W})")
        robots = np.asarray(kf["robot"], np.int32)
        if len(robots) != len(depths):
            raise ValueError(
                f"keyframe arrays disagree: {len(robots)} robot ids vs "
                f"{len(depths)} depths")
        if len(robots) and (robots.min() < 0
                            or robots.max() >= self.n_robots):
            raise ValueError(
                f"keyframe robot ids outside 0..{self.n_robots - 1}")

    def restore_keyframes(self, kf: dict) -> None:
        """Repopulate the ring from a keyframe sidecar — valid ONLY
        alongside a graph-preserving state restore (HTTP /load): the
        node anchors refer to the checkpointed graphs. Re-anchored
        resumes (demo --resume with fresh chains) must NOT call this;
        their rings stay empty (restore_grid clears them). Keyframes are
        tagged with each robot's LIVE state generation so later
        /initialpose resets still invalidate them."""
        self.validate_keyframes(kf)
        depths = np.asarray(kf["depths"], np.float32)
        robots = np.asarray(kf["robot"], np.int32)
        gens = [self.mapper.graph_snapshot(i)[0] if self.mapper is not None
                else 0 for i in range(self.n_robots)]
        rings: List[List[_Keyframe]] = [[] for _ in range(self.n_robots)]
        for k in range(len(robots)):
            i = int(robots[k])
            rings[i].append(_Keyframe(
                depth=depths[k],
                rel=np.asarray(kf["rels"][k], np.float32),
                node_idx=int(kf["node_idx"][k]),
                thins=int(kf["thins"][k]),
                gen=gens[i]))
        with self._lock:
            self._keyframes = rings
        # Fresh gating + thin replicas: thins_at() re-simulates from the
        # restored n_keyscans deterministically on next use.
        self._last_kf_pose = [None] * self.n_robots
        self._thin_sim = [_ThinSim(self.cfg.loop.max_poses)
                          for _ in range(self.n_robots)]

    def restore_grid(self, grid) -> None:
        g = self._jnp.asarray(grid)
        want = (self.cfg.voxel.size_z_cells, self.cfg.voxel.size_y_cells,
                self.cfg.voxel.size_x_cells)
        if g.shape != want:
            raise ValueError(
                f"voxel checkpoint shape {g.shape} != configured {want}")
        with self._lock:
            self.grid = g
            # Content changed without fusing: consumers keying caches on
            # n_images_fused must see a new revision or serve stale data.
            self.map_revision += 1
            # Checkpointed grids don't carry the keyframe ring; stored
            # keyframes belong to the pre-restore trajectory and a later
            # closure re-fuse from them would overwrite the restored map
            # with stale geometry.
            for ring in self._keyframes:
                ring.clear()
        self._last_kf_pose = [None] * self.n_robots

    def publish_points(self) -> None:
        """Occupied-voxel centres on `/voxel_points` (uniformly subsampled
        past `max_points`), the 3D analog of the mapper's /map publish."""
        from jax_mapping.bridge.messages import Header, VoxelPoints
        pts = self._V.occupied_voxel_centers(self.cfg.voxel,
                                             self.voxel_grid())
        if len(pts) > self.max_points:
            idx = np.linspace(0, len(pts) - 1, self.max_points) \
                .round().astype(int)
            pts = pts[idx]
        self.points_pub.publish(VoxelPoints(header=Header.now("map"),
                                            points=pts))

    def height_map_image(self) -> np.ndarray:
        """(Y, X) uint8 grayscale (`_height_to_gray` palette), flipud
        for image coords (the /map-image convention)."""
        return np.flipud(self._height_to_gray(self.height_map()))
