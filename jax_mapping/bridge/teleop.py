"""Teleoperation: joystick axes -> `/cmd_vel`, and manual-drive override.

The reference ships (install tree only) a `teleop_twist_joy` configuration
for a PS4 pad — axes 2/3 scaled to 0.20 m/s and 1.5 rad/s, deadman button
0, and `autorepeat_rate: 20.0` "to defeat command lag"
(`/root/reference/server/install/thymio_project/share/thymio_project/
config/joystick.yaml`, SURVEY.md §2.1). That node is external C++; this is
the framework-native equivalent: a `TeleopNode` with the same semantics
(deadman gating, scaling, fixed-rate autorepeat) fed by any axis source —
a real joystick event loop, the HTTP API, or tests.

The brain consumes `/cmd_vel` as a manual override while exploration is
stopped (the reference's RViz tool list already anticipates external
command sources, `server/rviz_config.rviz:186-198`).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional, Sequence

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import Twist
from jax_mapping.bridge.node import Node


@dataclasses.dataclass(frozen=True)
class JoystickConfig:
    """Field-for-field capability of the reference's joystick.yaml."""

    axis_linear: int = 3              # joystick.yaml axis_linear.x
    axis_angular: int = 2             # joystick.yaml axis_angular.yaw
    scale_linear: float = 0.20        # m/s full deflection
    scale_angular: float = 1.5        # rad/s full deflection
    deadman_button: int = 0           # enable_button: no motion unless held
    autorepeat_rate_hz: float = 20.0  # republish to defeat command lag


class TeleopNode(Node):
    """Joystick state -> rate-limited `/cmd_vel` Twists.

    `update(axes, buttons)` ingests the latest joystick sample (thread-safe,
    callable from any input loop); a timer republishes at
    `autorepeat_rate_hz` while the deadman is held and publishes a single
    zero Twist on release (the robot stops instead of coasting on the last
    command).
    """

    def __init__(self, bus: Bus, cfg: Optional[JoystickConfig] = None,
                 topic: str = "/cmd_vel", input_timeout_s: float = 0.5):
        super().__init__("teleop", bus)
        self.cfg = cfg or JoystickConfig()
        # Input liveness watchdog: autorepeat must not outlive its source.
        # If update() stops arriving (pad unplugged, event loop dead) the
        # node treats the deadman as released and stops the robot — without
        # this, endless republication keeps the brain's cmd_vel staleness
        # guard permanently fed with a stale command.
        self.input_timeout_s = input_timeout_s
        self._pub = self.create_publisher(topic)
        self._lock = threading.Lock()
        self._axes: Sequence[float] = ()
        self._buttons: Sequence[int] = ()
        self._last_update_t = -1e9
        self._was_active = False
        self.create_timer(1.0 / self.cfg.autorepeat_rate_hz, self._tick)

    def update(self, axes: Sequence[float], buttons: Sequence[int]) -> None:
        with self._lock:
            self._axes = tuple(axes)
            self._buttons = tuple(buttons)
            self._last_update_t = time.monotonic()

    def _tick(self) -> None:
        cfg = self.cfg
        now = time.monotonic()
        with self._lock:
            axes, buttons = self._axes, self._buttons
            live = now - self._last_update_t <= self.input_timeout_s
        deadman = (live and len(buttons) > cfg.deadman_button
                   and bool(buttons[cfg.deadman_button]))
        if deadman and len(axes) > max(cfg.axis_linear, cfg.axis_angular):
            self._pub.publish(Twist(
                linear_x=float(axes[cfg.axis_linear]) * cfg.scale_linear,
                angular_z=float(axes[cfg.axis_angular]) * cfg.scale_angular))
            self._was_active = True
        elif self._was_active:
            # Deadman released: one explicit stop.
            self._pub.publish(Twist(linear_x=0.0, angular_z=0.0))
            self._was_active = False
