"""Message types mirroring the ROS 2 wire contracts the reference speaks.

Field names and semantics follow the ROS 2 interface definitions for
`sensor_msgs/LaserScan`, `nav_msgs/OccupancyGrid`, `nav_msgs/Odometry`,
`geometry_msgs/TransformStamped` and `geometry_msgs/Twist` so that the rclpy
adapter (bridge/rclpy_adapter.py) is a field-for-field copy and everything
downstream of the reference's topics — RViz map display
(`/root/reference/server/rviz_config.rviz:152-165`), Nav2, the Flask image
endpoint (`server/thymio_project/thymio_project/main.py:241-279`) — keeps
working unchanged.

Payload arrays are numpy (host-side); device arrays live inside the models.
Occupancy values use the nav_msgs convention: -1 unknown, 0 free, 100
occupied (thresholding semantics of `server/.../main.py:259-263`).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Header:
    """std_msgs/Header: stamp in float seconds + frame id.

    The reference future-dates its odom TF stamp by +0.1 s to dodge
    transform_timeout (`server/.../main.py:205`, SURVEY.md Appendix B); this
    framework stamps honestly — the TF buffer interpolates/extrapolates
    instead.
    """

    stamp: float = 0.0
    frame_id: str = ""

    @staticmethod
    def now(frame_id: str = "") -> "Header":
        return Header(stamp=time.monotonic(), frame_id=frame_id)


@dataclasses.dataclass
class Pose2D:
    """Planar pose (x, y, theta) — the framework's native pose currency.

    Full 3D quaternions appear only at the message edge (`to_quaternion`,
    math of `euler_to_quaternion` at `server/.../main.py:31-36` restricted to
    yaw).
    """

    x: float = 0.0
    y: float = 0.0
    theta: float = 0.0

    def to_quaternion(self) -> Tuple[float, float, float, float]:
        """(qx, qy, qz, qw) for pure yaw."""
        half = self.theta * 0.5
        return (0.0, 0.0, math.sin(half), math.cos(half))

    @staticmethod
    def from_quaternion(qx: float, qy: float, qz: float, qw: float,
                        x: float = 0.0, y: float = 0.0) -> "Pose2D":
        yaw = math.atan2(2.0 * (qw * qz + qx * qy),
                         1.0 - 2.0 * (qy * qy + qz * qz))
        return Pose2D(x=x, y=y, theta=yaw)

    def as_array(self) -> np.ndarray:
        return np.array([self.x, self.y, self.theta], np.float32)

    @staticmethod
    def from_array(a) -> "Pose2D":
        return Pose2D(float(a[0]), float(a[1]), float(a[2]))


@dataclasses.dataclass
class LaserScan:
    """sensor_msgs/LaserScan — the `/scan` payload.

    Geometry defaults to the LD06 contract (counterclockwise, ~360 beams,
    `pi/src/thymio_project/launch/pi_hardware.launch.py:13-21`). `ranges`
    may be any length; the device path pads to static shape.
    """

    header: Header = dataclasses.field(default_factory=Header)
    angle_min: float = 0.0
    angle_max: float = 2.0 * math.pi
    angle_increment: float = 2.0 * math.pi / 360.0
    time_increment: float = 0.0
    scan_time: float = 0.1
    range_min: float = 0.02
    range_max: float = 12.0
    ranges: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.float32))
    intensities: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.float32))


@dataclasses.dataclass
class DepthImage:
    """sensor_msgs/Image (32FC1 depth) — the `{ns}depth` payload.

    Depth is metres along the OPTICAL AXIS (what real depth sensors
    report), 0.0 = no return; intrinsics live in DepthCamConfig, not the
    message (one camera model per deployment, the reference's
    one-static-TF-per-sensor convention)."""

    header: Header = dataclasses.field(default_factory=Header)
    depth: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 0), np.float32))

    @property
    def height(self) -> int:
        return self.depth.shape[0]

    @property
    def width(self) -> int:
        return self.depth.shape[1]


@dataclasses.dataclass
class VoxelPoints:
    """Occupied-voxel centres in the map frame — the 3D map's export
    payload (`/voxel_points`; the rclpy adapter republishes it as
    sensor_msgs/PointCloud2 for RViz)."""

    header: Header = dataclasses.field(default_factory=Header)
    points: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0, 3), np.float32))


@dataclasses.dataclass
class MapMetaData:
    """nav_msgs/MapMetaData: resolution + dimensions + origin pose."""

    map_load_time: float = 0.0
    resolution: float = 0.05           # slam_config.yaml:26
    width: int = 0
    height: int = 0
    origin: Pose2D = dataclasses.field(default_factory=Pose2D)


@dataclasses.dataclass
class OccupancyGrid:
    """nav_msgs/OccupancyGrid — the `/map` payload.

    `data` is int8 row-major from the origin (bottom-left), values in
    {-1, 0..100}; exactly what RViz's Map display and the reference's
    `/map-image` endpoint consume (`server/.../main.py:256-266` reshapes and
    flips it for image coordinates).
    """

    header: Header = dataclasses.field(default_factory=Header)
    info: MapMetaData = dataclasses.field(default_factory=MapMetaData)
    data: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, np.int8))

    def as_image_array(self) -> np.ndarray:
        """Grayscale uint8 view in image coordinates.

        Exact semantics of the reference endpoint (`server/.../main.py:
        256-266`): 127 unknown, 255 free (value 0), 0 occupied (value 100),
        then flipud from ROS bottom-left origin to image top-left.
        """
        grid = np.asarray(self.data, np.int16).reshape(
            self.info.height, self.info.width)
        img = np.full(grid.shape, 127, np.uint8)
        img[grid == 0] = 255
        img[grid == 100] = 0
        return np.flipud(img)


@dataclasses.dataclass
class Twist:
    """geometry_msgs/Twist restricted to the planar components the
    differential drive can realise (`/cmd_vel`, report.pdf §III.A)."""

    linear_x: float = 0.0
    angular_z: float = 0.0


@dataclasses.dataclass
class Odometry:
    """nav_msgs/Odometry — the `/odom` payload (`server/.../main.py:217-224`:
    pose + twist in the odom frame, child base_link)."""

    header: Header = dataclasses.field(default_factory=Header)
    child_frame_id: str = "base_link"
    pose: Pose2D = dataclasses.field(default_factory=Pose2D)
    twist: Twist = dataclasses.field(default_factory=Twist)


@dataclasses.dataclass
class TransformStamped:
    """geometry_msgs/TransformStamped restricted to SE(2) + z offset.

    Carries the frames the reference's TF tree needs (SURVEY.md §1 L1):
    map->odom (SLAM correction), odom->base_link (odometry), static
    base_link->base_laser with z=0.12 m
    (`pi/src/thymio_project/launch/pi_hardware.launch.py:26-30`).
    """

    header: Header = dataclasses.field(default_factory=Header)
    child_frame_id: str = ""
    x: float = 0.0
    y: float = 0.0
    z: float = 0.0
    theta: float = 0.0

    def compose(self, other: "TransformStamped") -> "TransformStamped":
        """self ∘ other: transform of other's child expressed in self's
        parent frame (standard SE(2) composition, z additive)."""
        c, s = math.cos(self.theta), math.sin(self.theta)
        return TransformStamped(
            header=Header(stamp=max(self.header.stamp, other.header.stamp),
                          frame_id=self.header.frame_id),
            child_frame_id=other.child_frame_id,
            x=self.x + c * other.x - s * other.y,
            y=self.y + s * other.x + c * other.y,
            z=self.z + other.z,
            theta=self.theta + other.theta,
        )

    def inverse(self) -> "TransformStamped":
        c, s = math.cos(self.theta), math.sin(self.theta)
        return TransformStamped(
            header=Header(stamp=self.header.stamp,
                          frame_id=self.child_frame_id),
            child_frame_id=self.header.frame_id,
            x=-(c * self.x + s * self.y),
            y=-(-s * self.x + c * self.y),
            z=-self.z,
            theta=-self.theta,
        )


@dataclasses.dataclass
class FrontierArray:
    """Framework-native `/frontiers` payload: clustered frontier targets and
    the per-robot assignment computed on device (the capability the
    reference's report defers to future work, report.pdf §VI.2)."""

    header: Header = dataclasses.field(default_factory=Header)
    targets_xy: np.ndarray = dataclasses.field(          # (K, 2) metres
        default_factory=lambda: np.zeros((0, 2), np.float32))
    sizes: np.ndarray = dataclasses.field(               # (K,) cells
        default_factory=lambda: np.zeros(0, np.int32))
    assignment: np.ndarray = dataclasses.field(          # (R,) index into K or -1
        default_factory=lambda: np.zeros(0, np.int32))
    #: `map_revision` the frontier set was COMPUTED at (-1 = revision
    #: tracking off): lets consumers correlate an assignment with the
    #: exact map content that produced it — a skipped publish re-ships
    #: the original compute's revision, not the current one.
    map_revision: int = -1


@dataclasses.dataclass
class Path:
    """`/plan` payload: the global planner's world-frame waypoint list
    (nav_msgs/Path at the rclpy boundary — the topic Nav2's planners
    publish; the reference's SetGoal tool had no planner behind it,
    `server/rviz_config.rviz:193-198`). Empty poses_xy = no plan (goal
    unreachable or already reached)."""

    header: Header = dataclasses.field(default_factory=Header)
    poses_xy: np.ndarray = dataclasses.field(            # (L, 2) metres
        default_factory=lambda: np.zeros((0, 2), np.float32))


@dataclasses.dataclass
class Waypoint:
    """`/goal_waypoint` payload: the planner's lookahead steering target.

    The brain steers toward (x, y) instead of the raw goal while the
    message is fresher than PlannerConfig.waypoint_ttl_s and `reachable`;
    goal_x/goal_y echo the goal the plan was computed FOR, so a steering
    target from a superseded goal is never applied to a new one. `robot`
    addresses the fleet member (frontier waypoints are per-robot; the
    manual nav goal is robot 0's, brain._goal_cb's convention)."""

    header: Header = dataclasses.field(default_factory=Header)
    x: float = 0.0
    y: float = 0.0
    reachable: bool = False
    goal_x: float = 0.0
    goal_y: float = 0.0
    robot: int = 0


@dataclasses.dataclass
class Heartbeat:
    """`/heartbeat` payload: one node's liveness beat for the Supervisor.

    The reference has nothing like it — node death is discovered by a
    human watching RViz go stale (SURVEY.md §5). Every framework node
    publishes a beat each loop iteration; the supervisor declares a node
    dead after `ResilienceConfig.supervisor_missed_beats` of ITS ticks
    without one and applies the restart policy. `seq` is the node's own
    monotonically increasing loop counter (the deterministic time base —
    wall stamps ride along in the header for humans); `payload` carries
    node-specific health extras (the LD06 transport's reconnect counters
    and current backoff, the brain's link state, queue depths)."""

    header: Header = dataclasses.field(default_factory=Header)
    node: str = ""
    seq: int = 0
    payload: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class GraphMarkers:
    """`/graph` payload: the fleet's pose graphs for visualization — the
    capability slam_toolbox's interactive mode renders in RViz (graph
    nodes + constraints; slam_config.yaml:32 enables it, the reference
    never used it). Flat arrays: nodes with their owning robot, edges as
    endpoint pairs, loop edges flagged (non-consecutive constraints)."""

    header: Header = dataclasses.field(default_factory=Header)
    nodes_xy: np.ndarray = dataclasses.field(        # (N, 2) metres
        default_factory=lambda: np.zeros((0, 2), np.float32))
    node_robot: np.ndarray = dataclasses.field(      # (N,)
        default_factory=lambda: np.zeros(0, np.int32))
    edges_xy: np.ndarray = dataclasses.field(        # (E, 2, 2)
        default_factory=lambda: np.zeros((0, 2, 2), np.float32))
    edge_is_loop: np.ndarray = dataclasses.field(    # (E,)
        default_factory=lambda: np.zeros(0, bool))


def occupancy_from_logodds(logodds: np.ndarray, occ_threshold: float,
                           free_threshold: float, resolution: float,
                           origin_xy: Tuple[float, float],
                           stamp: Optional[float] = None,
                           frame_id: str = "map") -> OccupancyGrid:
    """Threshold a host log-odds array (row 0 = min-y) into nav_msgs values.

    The int8 {-1, 0, 100} trichotomy only exists at this export edge
    (SURVEY.md §7 step 1); on device the grid stays float log-odds.
    """
    lo = np.asarray(logodds)
    data = np.full(lo.shape, -1, np.int8)
    data[lo <= free_threshold] = 0
    data[lo >= occ_threshold] = 100
    h, w = lo.shape
    return OccupancyGrid(
        header=Header(stamp=time.monotonic() if stamp is None else stamp,
                      frame_id=frame_id),
        info=MapMetaData(resolution=resolution, width=w, height=h,
                         origin=Pose2D(origin_xy[0], origin_xy[1], 0.0)),
        data=data.reshape(-1),
    )
