"""LD06 ingest node: wire bytes -> `sensor_msgs/LaserScan` on the bus.

The role of the reference driver's ROS node TU (`demo.cpp` in SURVEY.md
§2.3: param handling, LaserScan assembly/publish) on top of the native C++
parse/filter pipeline (`native.ld06`). A transport callable supplies bytes —
a serial port read, a TCP socket, a recorded dump, or the simulator's
`encode_packets` output — so the identical node runs against hardware and
sim. Publishes Best-Effort like the reference's `/scan` (report.pdf §V.A).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import Header, LaserScan
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.qos import qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import ScanConfig


class Ld06IngestNode(Node):
    """Poll a byte transport, publish complete rotations."""

    def __init__(self, scan_cfg: ScanConfig, bus: Bus,
                 transport: Callable[[], bytes],
                 topic: str = "scan", frame_id: str = "base_laser",
                 tf: Optional[TfTree] = None,
                 poll_period_s: float = 0.01, realtime: bool = True,
                 min_confidence: int = 15, band_m: float = 0.15):
        super().__init__("ld06_ingest", bus, tf)
        from jax_mapping.native import Ld06Parser

        self.scan_cfg = scan_cfg
        self.transport = transport
        self.frame_id = frame_id
        self.parser = Ld06Parser(n_beams=scan_cfg.n_beams,
                                 min_confidence=min_confidence,
                                 band_m=band_m)
        self.pub = self.create_publisher(topic, qos_sensor_data)
        self.n_scans_published = 0
        #: Scans published under a cross-process acquisition context
        #: (the transport decoded a trace frame): the wire context is
        #: made CURRENT around the publish, so the bus derives the
        #: publish's TraceContext as a CHILD of the remote acquisition
        #: span — a fused scan's span chain crosses the process
        #: boundary back to the Pi-side acquisition. Attribution is
        #: per-poll (the freshest frame's context covers the rotations
        #: completed by that poll's bytes — frames outpace rotations,
        #: so the approximation is one frame at most).
        self.n_traced_publishes = 0
        # Heartbeat for the Supervisor; the payload surfaces the
        # transport's reconnect pressure (TcpTransport.stats: counters +
        # current jittered backoff) so an operator sees a flapping lidar
        # bridge on /status without shelling into the pi.
        from jax_mapping.resilience.supervisor import Heartbeater
        self._heartbeater = Heartbeater(self)
        if realtime:
            self.create_timer(poll_period_s, self.poll)

    def poll(self) -> None:
        """Drain the transport, publish any completed rotations."""
        data = self.transport()
        if data:
            self.parser.feed(data)
        # Cross-process trace propagation: a framing transport exposes
        # the freshest acquisition TraceContext decoded from the wire;
        # with a tracer armed, the scan publish runs under it so the
        # bus chains the publish as a child of the REMOTE acquisition
        # span (absent either — legacy peer, tracing off — the publish
        # roots locally, the pre-frames behavior exactly).
        tracer = getattr(self.bus, "tracer", None)
        wire_ctx = None
        if tracer is not None:
            ctx_fn = getattr(self.transport, "trace_context", None)
            if callable(ctx_fn):
                wire_ctx = ctx_fn()
        while True:
            out = self.parser.take_scan()
            if out is None:
                break
            ranges, intensities = out
            sc = self.scan_cfg
            msg = LaserScan(
                header=Header(stamp=time.monotonic(),
                              frame_id=self.frame_id),
                angle_min=sc.angle_min_rad,
                angle_increment=sc.angle_increment_rad,
                scan_time=(360.0 / self.parser.speed_deg_s
                           if self.parser.speed_deg_s > 0 else 0.1),
                range_min=sc.range_min_m,
                range_max=sc.range_max_m,
                ranges=np.asarray(ranges, np.float32),
                intensities=np.asarray(intensities, np.float32))
            if wire_ctx is not None:
                with tracer.use(wire_ctx):
                    self.pub.publish(msg)
                self.n_traced_publishes += 1
            else:
                self.pub.publish(msg)
            self.n_scans_published += 1
        payload = {"scans_published": self.n_scans_published}
        stats = getattr(self.transport, "stats", None)
        if callable(stats):
            payload["transport"] = stats()
        self._heartbeater.beat(payload)
