"""Byte transports for the LD06 ingest node: serial, TCP, UDP.

The reference vendors the ldrobot driver with two transport backends —
UART serial (`pi_hardware.launch.py:17-18`, /dev/ttyUSB0 @ 230400) and a
TCP/UDP network path (`network_socket_interface_linux.cpp`, SURVEY.md
§2.3) for lidars behind a serial-to-ethernet bridge. `Ld06IngestNode`
takes any zero-argument callable returning the freshest bytes; these are
the concrete implementations for real deployments, stdlib-only:

  * `SerialTransport` — a tty put into raw mode at 230400 baud via
    termios (no pyserial in this image, none needed: reading a configured
    tty is just os.read);
  * `TcpTransport` — client socket to a serial-device server, with
    bounded-backoff auto-reconnect (the lidar bridge may boot after us);
  * `UdpTransport` — bound datagram socket (the vendored driver's UDP
    server mode).

All are non-blocking: they return b"" when nothing is pending, so the
node's 100 Hz poll timer never stalls the executor, and all are safe to
`close()` from another thread. Tests drive them with ptys and localhost
sockets carrying `native.ld06.encode_packets` bytes — the same
spec-conformant stream real hardware produces.

Cross-process trace propagation (the freshness-SLO tier): the
reference system is distributed by construction — acquisition on the
Pi, fusion on the PC — and PR 9's causal tracing stopped dead at this
socket. A NEW-protocol sender wraps its byte chunks in versioned
frames (`encode_frame`) whose header can carry a compact `TraceContext`
(trace_id / span_id / parent, 24 bytes big-endian); the receiving
`TcpTransport` auto-detects the protocol PER CONNECTION from the first
bytes (a legacy peer's raw LD06 stream never starts with the frame
magic — the Pi-side process may lag the PC-side on upgrade, absent
frames simply mean "legacy peer") and `FrameDecoder` strips headers,
handing the ingest node the payload byte stream plus the freshest
acquisition context to re-establish around its scan publish — so a
scan's fuse span parents back to its acquisition across the process
boundary. Robustness contract: a truncated or garbage frame header
DEGRADES to untraced delivery with a counter (`n_frame_errors`),
never a disconnect — the skipped bytes flow through raw and the LD06
parser's own checksum resync recovers; symmetrically, a framed stream
fed to a LEGACY receiver still parses (headers are small inter-packet
garbage the parser skips), so mismatched upgrades interop in both
directions. Framing is trace-plumbing only: with no Tracer armed the
contexts are decoded and dropped — bit-inert, the ObsConfig doctrine.
"""

from __future__ import annotations

import errno
import os
import random
import socket
import time
from typing import Optional

from jax_mapping.obs.trace import TraceContext

#: Frame magic. First byte deliberately != 0x54 (the LD06 packet
#: header): a fresh connection's first bytes decide the protocol, and
#: a legacy LD06 stream can never open with this pair.
FRAME_MAGIC = b"\xa9\x4c"
FRAME_VERSION = 1
#: Header flags: bit0 = a 24-byte TraceContext follows the length.
_FLAG_CTX = 0x01
#: Sanity bound on a frame's payload length: a corrupted length field
#: must not make the decoder buffer unbounded garbage waiting for a
#: frame that never completes.
MAX_FRAME_PAYLOAD = 1 << 20
_BASE_HEADER = 8                      # magic(2) ver(1) flags(1) len(4)
_CTX_BYTES = 24


def encode_frame(payload: bytes,
                 ctx: Optional[TraceContext] = None) -> bytes:
    """One wire frame: header (+ optional trace context) + payload."""
    flags = _FLAG_CTX if ctx is not None else 0
    head = FRAME_MAGIC + bytes((FRAME_VERSION, flags)) \
        + len(payload).to_bytes(4, "little")
    if ctx is not None:
        head += ctx.trace_id.to_bytes(8, "big") \
            + ctx.span_id.to_bytes(8, "big") \
            + ctx.parent_span.to_bytes(8, "big")
    return head + payload


class FrameEncoder:
    """Sender-side helper (the Pi-side acquisition process): wraps each
    outgoing chunk in a frame, deriving one acquisition span per frame
    from the sender's Tracer when armed (ids are deterministic from the
    sender's seed — the stream-identity contract holds per process).
    `tracer=None` emits context-less frames (still versioned: the
    receiver knows it is talking to a new peer)."""

    def __init__(self, tracer=None, span_name: str = "ld06.acquire"):
        self.tracer = tracer
        self.span_name = span_name
        self.n_frames = 0

    def encode(self, payload: bytes) -> bytes:
        self.n_frames += 1
        ctx = None
        if self.tracer is not None:
            ctx = self.tracer.emit(self.span_name, key=self.n_frames)
        return encode_frame(payload, ctx)


class FrameDecoder:
    """Receiver-side stream deframer with legacy auto-detection.

    Modes: `unknown` (deciding on the connection's first bytes) →
    `framed` or `legacy`. Legacy mode is a pure passthrough — the
    pre-framing byte path bit-for-bit. Framed mode strips headers and
    records the freshest frame's TraceContext; any malformed header
    (bad magic mid-stream, wrong version, unknown flags, oversize
    length) counts an error, clears the context, and RESYNCS to the
    next magic while delivering the skipped bytes raw — degraded
    untraced delivery, never a protocol abort."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        """Per-connection state: a reconnected peer renegotiates."""
        self.mode = "unknown"
        self._buf = bytearray()
        self.last_ctx: Optional[TraceContext] = None
        self.n_frames = 0
        self.n_traced_frames = 0
        self.n_frame_errors = 0

    def feed(self, data: bytes) -> bytes:
        """Consume raw socket bytes, return payload bytes (possibly
        b"": an incomplete frame waits in the buffer)."""
        if self.mode == "legacy":
            return data
        self._buf += data
        if self.mode == "unknown":
            if len(self._buf) >= 1 \
                    and self._buf[0] != FRAME_MAGIC[0]:
                self.mode = "legacy"
            elif len(self._buf) >= 2 \
                    and bytes(self._buf[:2]) != FRAME_MAGIC:
                self.mode = "legacy"
            elif len(self._buf) >= 2:
                self.mode = "framed"
            if self.mode == "legacy":
                out = bytes(self._buf)
                self._buf = bytearray()
                return out
            if self.mode == "unknown":
                return b""
        return self._parse_frames()

    def _parse_frames(self) -> bytes:
        out = bytearray()
        buf = self._buf
        while True:
            if len(buf) < 2:
                break
            if bytes(buf[:2]) != FRAME_MAGIC:
                # Garbage between frames: resync to the next magic and
                # deliver the skipped bytes raw (the LD06 parser's own
                # resync copes) — degraded, counted, never an abort.
                self.n_frame_errors += 1
                self.last_ctx = None
                idx = buf.find(FRAME_MAGIC, 1)
                if idx < 0:
                    # Keep the final byte: a magic pair may straddle
                    # this read and the next.
                    out += buf[:-1]
                    del buf[:-1]
                    break
                out += buf[:idx]
                del buf[:idx]
                continue
            if len(buf) < _BASE_HEADER:
                break
            ver, flags = buf[2], buf[3]
            length = int.from_bytes(bytes(buf[4:8]), "little")
            if ver != FRAME_VERSION or (flags & ~_FLAG_CTX) \
                    or length > MAX_FRAME_PAYLOAD:
                # Corrupted or future header: drop the magic pair and
                # rescan — its remains deliver raw via the branch above.
                self.n_frame_errors += 1
                self.last_ctx = None
                del buf[:2]
                continue
            header = _BASE_HEADER + (_CTX_BYTES if flags & _FLAG_CTX
                                     else 0)
            if len(buf) < header + length:
                break                          # incomplete: wait
            if flags & _FLAG_CTX:
                raw = bytes(buf[_BASE_HEADER:header])
                self.last_ctx = TraceContext(
                    int.from_bytes(raw[0:8], "big"),
                    int.from_bytes(raw[8:16], "big"),
                    int.from_bytes(raw[16:24], "big"))
                self.n_traced_frames += 1
            else:
                self.last_ctx = None
            out += buf[header:header + length]
            del buf[:header + length]
            self.n_frames += 1
        return bytes(out)

    def stats(self) -> dict:
        return {"mode": self.mode, "n_frames": self.n_frames,
                "n_traced_frames": self.n_traced_frames,
                "n_frame_errors": self.n_frame_errors}


class SerialTransport:
    """Raw-mode tty reader (the reference's UART path)."""

    def __init__(self, path: str, baud: int = 230400):
        import termios
        self.path = path
        self._fd = os.open(path, os.O_RDWR | os.O_NOCTTY | os.O_NONBLOCK)
        try:
            attrs = termios.tcgetattr(self._fd)
            # cfmakeraw semantics: no line discipline mangling the binary
            # packet stream.
            attrs[0] = 0                                   # iflag
            attrs[1] = 0                                   # oflag
            attrs[2] = termios.CS8 | termios.CREAD | termios.CLOCAL
            attrs[3] = 0                                   # lflag
            rate = getattr(termios, f"B{baud}", None)
            if rate is not None:
                attrs[4] = attrs[5] = rate                 # ispeed/ospeed
            termios.tcsetattr(self._fd, termios.TCSANOW, attrs)
        except termios.error:
            # Not a real tty (a pty pair or fifo in tests): raw bytes
            # flow regardless; baud only means something on real UARTs.
            pass

    def __call__(self) -> bytes:
        try:
            return os.read(self._fd, 4096)
        except BlockingIOError:
            return b""
        except OSError:
            return b""

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


class TcpTransport:
    """Auto-reconnecting client to a lidar TCP server.

    Fully non-blocking, including the DIAL: connects via connect_ex on a
    non-blocking socket and completes the handshake across poll calls (a
    blocking create_connection would stall the shared executor up to its
    timeout every backoff window while the lidar bridge is down).
    Counters: `n_connects` counts every established connection;
    `n_reconnects` only those after a previous one existed (a healthy
    single-connection session reads 0).

    Backoff carries SEEDED jitter: each scheduled retry waits
    `backoff * (1 + jitter * rng())`. Without it, a fleet of clients
    that all lost the same lidar bridge redial in lockstep and hammer
    it the instant it returns (the thundering-herd reconnect the
    resilience subsystem's Supervisor backoff also avoids); the seed
    keeps chaos tests reproducible. `last_backoff_s` and the counters
    feed the ingest node's heartbeat payload.

    Trace-frame deframing (`framed=None`, the default): each
    connection auto-detects whether the peer speaks the versioned
    frame protocol (FrameDecoder) — a legacy raw-byte peer passes
    through bit-for-bit, a framing peer's headers are stripped and the
    freshest acquisition TraceContext is exposed via
    `trace_context()`. `framed=False` pins the pre-framing passthrough
    exactly (the behavior of a receiver that predates frames — the
    interop tests' "old PC-side" stand-in)."""

    def __init__(self, host: str, port: int,
                 reconnect_backoff_s: float = 0.5,
                 max_backoff_s: float = 5.0,
                 jitter: float = 0.25, seed: Optional[int] = None,
                 framed: Optional[bool] = None):
        self.host, self.port = host, port
        #: Per-connection deframer; None = the legacy receiver
        #: (framed=False), which never inspects the stream.
        self._decoder = FrameDecoder() if framed is not False else None
        self._sock: Optional[socket.socket] = None
        self._pending: Optional[socket.socket] = None
        self._backoff = reconnect_backoff_s
        self._backoff0 = reconnect_backoff_s
        self._max_backoff = max_backoff_s
        self._jitter = jitter
        self._rng = random.Random(seed)
        self._next_attempt = 0.0
        self.n_connects = 0
        self.n_reconnects = 0
        #: The jittered wait the most recent failure scheduled (0.0
        #: while connected) — exported in heartbeats.
        self.last_backoff_s = 0.0
        self._closed = False

    def _jittered(self, base_s: float) -> float:
        return base_s * (1.0 + self._jitter * self._rng.random())

    def _fail_attempt(self) -> None:
        if self._pending is not None:
            try:
                self._pending.close()
            except OSError:
                pass
            self._pending = None
        self.last_backoff_s = self._jittered(self._backoff)
        self._next_attempt = time.monotonic() + self.last_backoff_s
        self._backoff = min(self._backoff * 2, self._max_backoff)

    def _established(self, s: socket.socket) -> None:
        if self.n_connects > 0:
            self.n_reconnects += 1
        self.n_connects += 1
        self._sock = s
        self._pending = None
        self._backoff = self._backoff0
        self.last_backoff_s = 0.0
        if self._decoder is not None:
            # A new incarnation of the peer renegotiates the protocol
            # (the lidar bridge may have been upgraded/downgraded
            # across its reboot).
            self._decoder.reset()

    def trace_context(self) -> Optional[TraceContext]:
        """The freshest acquisition TraceContext decoded from the wire
        (None: legacy peer, context-less frames, or framing off) — the
        ingest node re-establishes it around its scan publish."""
        return None if self._decoder is None else self._decoder.last_ctx

    def stats(self) -> dict:
        """Heartbeat-payload export (ld06_node): reconnect pressure and
        the current backoff posture at a glance, plus the wire
        protocol's framing posture (mode + degraded-frame counter)."""
        out = {"connected": self._sock is not None,
               "n_connects": self.n_connects,
               "n_reconnects": self.n_reconnects,
               "backoff_s": round(self.last_backoff_s, 4)}
        if self._decoder is not None:
            out["framing"] = self._decoder.stats()
        return out

    def _connect_step(self) -> None:
        """Advance the non-blocking dial one step; never blocks."""
        import select
        now = time.monotonic()
        if self._closed:
            return
        if self._pending is None:
            if now < self._next_attempt:
                return
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            rc = s.connect_ex((self.host, self.port))
            if rc == 0:
                self._established(s)
            elif rc in (errno.EINPROGRESS, errno.EWOULDBLOCK,
                        errno.EAGAIN):
                self._pending = s
            else:
                self._pending = s
                self._fail_attempt()
            return
        # Handshake in flight: writable == resolved (then check SO_ERROR).
        _, w, _ = select.select([], [self._pending], [], 0)
        if not w:
            return
        err = self._pending.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err == 0:
            self._established(self._pending)
        else:
            self._fail_attempt()

    def __call__(self) -> bytes:
        s = self._sock                       # snapshot: close() may race
        if s is None:
            self._connect_step()
            s = self._sock
            if s is None:
                return b""
        try:
            data = s.recv(4096)
        except BlockingIOError:
            return b""
        except OSError:
            data = b""
        if not data:
            # Peer closed (lidar bridge rebooted): drop and re-dial.
            try:
                s.close()
            except OSError:
                pass
            if self._sock is s:
                self._sock = None
            self.last_backoff_s = self._jittered(self._backoff0)
            self._next_attempt = time.monotonic() + self.last_backoff_s
            return b""
        if self._decoder is not None:
            return self._decoder.feed(data)
        return data

    def close(self) -> None:
        self._closed = True
        for s in (self._sock, self._pending):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = None
        self._pending = None


class UdpTransport:
    """Bound datagram receiver (the vendored driver's UDP mode)."""

    def __init__(self, bind_host: str = "0.0.0.0", bind_port: int = 8889):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind_host, bind_port))
        self._sock.setblocking(False)
        self.port = self._sock.getsockname()[1]

    def __call__(self) -> bytes:
        out = b""
        # Drain every pending datagram: packets are 47 bytes and arrive
        # faster than the poll when the lidar bursts a rotation.
        while True:
            try:
                chunk, _addr = self._sock.recvfrom(4096)
            except BlockingIOError:
                break
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    break
                return out
            out += chunk
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
