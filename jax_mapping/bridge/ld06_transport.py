"""Byte transports for the LD06 ingest node: serial, TCP, UDP.

The reference vendors the ldrobot driver with two transport backends —
UART serial (`pi_hardware.launch.py:17-18`, /dev/ttyUSB0 @ 230400) and a
TCP/UDP network path (`network_socket_interface_linux.cpp`, SURVEY.md
§2.3) for lidars behind a serial-to-ethernet bridge. `Ld06IngestNode`
takes any zero-argument callable returning the freshest bytes; these are
the concrete implementations for real deployments, stdlib-only:

  * `SerialTransport` — a tty put into raw mode at 230400 baud via
    termios (no pyserial in this image, none needed: reading a configured
    tty is just os.read);
  * `TcpTransport` — client socket to a serial-device server, with
    bounded-backoff auto-reconnect (the lidar bridge may boot after us);
  * `UdpTransport` — bound datagram socket (the vendored driver's UDP
    server mode).

All are non-blocking: they return b"" when nothing is pending, so the
node's 100 Hz poll timer never stalls the executor, and all are safe to
`close()` from another thread. Tests drive them with ptys and localhost
sockets carrying `native.ld06.encode_packets` bytes — the same
spec-conformant stream real hardware produces.
"""

from __future__ import annotations

import errno
import os
import random
import socket
import time
from typing import Optional


class SerialTransport:
    """Raw-mode tty reader (the reference's UART path)."""

    def __init__(self, path: str, baud: int = 230400):
        import termios
        self.path = path
        self._fd = os.open(path, os.O_RDWR | os.O_NOCTTY | os.O_NONBLOCK)
        try:
            attrs = termios.tcgetattr(self._fd)
            # cfmakeraw semantics: no line discipline mangling the binary
            # packet stream.
            attrs[0] = 0                                   # iflag
            attrs[1] = 0                                   # oflag
            attrs[2] = termios.CS8 | termios.CREAD | termios.CLOCAL
            attrs[3] = 0                                   # lflag
            rate = getattr(termios, f"B{baud}", None)
            if rate is not None:
                attrs[4] = attrs[5] = rate                 # ispeed/ospeed
            termios.tcsetattr(self._fd, termios.TCSANOW, attrs)
        except termios.error:
            # Not a real tty (a pty pair or fifo in tests): raw bytes
            # flow regardless; baud only means something on real UARTs.
            pass

    def __call__(self) -> bytes:
        try:
            return os.read(self._fd, 4096)
        except BlockingIOError:
            return b""
        except OSError:
            return b""

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass


class TcpTransport:
    """Auto-reconnecting client to a lidar TCP server.

    Fully non-blocking, including the DIAL: connects via connect_ex on a
    non-blocking socket and completes the handshake across poll calls (a
    blocking create_connection would stall the shared executor up to its
    timeout every backoff window while the lidar bridge is down).
    Counters: `n_connects` counts every established connection;
    `n_reconnects` only those after a previous one existed (a healthy
    single-connection session reads 0).

    Backoff carries SEEDED jitter: each scheduled retry waits
    `backoff * (1 + jitter * rng())`. Without it, a fleet of clients
    that all lost the same lidar bridge redial in lockstep and hammer
    it the instant it returns (the thundering-herd reconnect the
    resilience subsystem's Supervisor backoff also avoids); the seed
    keeps chaos tests reproducible. `last_backoff_s` and the counters
    feed the ingest node's heartbeat payload."""

    def __init__(self, host: str, port: int,
                 reconnect_backoff_s: float = 0.5,
                 max_backoff_s: float = 5.0,
                 jitter: float = 0.25, seed: Optional[int] = None):
        self.host, self.port = host, port
        self._sock: Optional[socket.socket] = None
        self._pending: Optional[socket.socket] = None
        self._backoff = reconnect_backoff_s
        self._backoff0 = reconnect_backoff_s
        self._max_backoff = max_backoff_s
        self._jitter = jitter
        self._rng = random.Random(seed)
        self._next_attempt = 0.0
        self.n_connects = 0
        self.n_reconnects = 0
        #: The jittered wait the most recent failure scheduled (0.0
        #: while connected) — exported in heartbeats.
        self.last_backoff_s = 0.0
        self._closed = False

    def _jittered(self, base_s: float) -> float:
        return base_s * (1.0 + self._jitter * self._rng.random())

    def _fail_attempt(self) -> None:
        if self._pending is not None:
            try:
                self._pending.close()
            except OSError:
                pass
            self._pending = None
        self.last_backoff_s = self._jittered(self._backoff)
        self._next_attempt = time.monotonic() + self.last_backoff_s
        self._backoff = min(self._backoff * 2, self._max_backoff)

    def _established(self, s: socket.socket) -> None:
        if self.n_connects > 0:
            self.n_reconnects += 1
        self.n_connects += 1
        self._sock = s
        self._pending = None
        self._backoff = self._backoff0
        self.last_backoff_s = 0.0

    def stats(self) -> dict:
        """Heartbeat-payload export (ld06_node): reconnect pressure and
        the current backoff posture at a glance."""
        return {"connected": self._sock is not None,
                "n_connects": self.n_connects,
                "n_reconnects": self.n_reconnects,
                "backoff_s": round(self.last_backoff_s, 4)}

    def _connect_step(self) -> None:
        """Advance the non-blocking dial one step; never blocks."""
        import select
        now = time.monotonic()
        if self._closed:
            return
        if self._pending is None:
            if now < self._next_attempt:
                return
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            rc = s.connect_ex((self.host, self.port))
            if rc == 0:
                self._established(s)
            elif rc in (errno.EINPROGRESS, errno.EWOULDBLOCK,
                        errno.EAGAIN):
                self._pending = s
            else:
                self._pending = s
                self._fail_attempt()
            return
        # Handshake in flight: writable == resolved (then check SO_ERROR).
        _, w, _ = select.select([], [self._pending], [], 0)
        if not w:
            return
        err = self._pending.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err == 0:
            self._established(self._pending)
        else:
            self._fail_attempt()

    def __call__(self) -> bytes:
        s = self._sock                       # snapshot: close() may race
        if s is None:
            self._connect_step()
            s = self._sock
            if s is None:
                return b""
        try:
            data = s.recv(4096)
        except BlockingIOError:
            return b""
        except OSError:
            data = b""
        if not data:
            # Peer closed (lidar bridge rebooted): drop and re-dial.
            try:
                s.close()
            except OSError:
                pass
            if self._sock is s:
                self._sock = None
            self.last_backoff_s = self._jittered(self._backoff0)
            self._next_attempt = time.monotonic() + self.last_backoff_s
            return b""
        return data

    def close(self) -> None:
        self._closed = True
        for s in (self._sock, self._pending):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = None
        self._pending = None


class UdpTransport:
    """Bound datagram receiver (the vendored driver's UDP mode)."""

    def __init__(self, bind_host: str = "0.0.0.0", bind_port: int = 8889):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind_host, bind_port))
        self._sock.setblocking(False)
        self.port = self._sock.getsockname()[1]

    def __call__(self) -> bytes:
        out = b""
        # Drain every pending datagram: packets are 47 bytes and arrive
        # faster than the poll when the lidar bursts a rotation.
        while True:
            try:
                chunk, _addr = self._sock.recvfrom(4096)
            except BlockingIOError:
                break
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    break
                return out
            out += chunk
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
