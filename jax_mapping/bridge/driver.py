"""Thymio device driver abstraction + simulated fleet backend.

The reference talks to the robot through `thymiodirect`'s dict-style
variable access — `th[node_id]["motor.left.target"] = v`
(`/root/reference/server/thymio_project/thymio_project/main.py:15,66-68,
96-99,195-196`). This module keeps that exact access surface so the brain
node reads identically against real hardware or the simulator, and ports the
pi variant's robustness patterns (SURVEY.md §3.6, §5 failure detection):

* bounded connect retries (3, `pi/src/.../main.py:32,56-64`),
* a connect timeout imposed from outside because the library call can hang
  (worker thread + join(3 s), `pi/src/.../main.py:111-148`),
* post-connect smoke test (read a variable, blink LEDs, `:151-157`),
* offline/degraded mode instead of crashing (`:66-67`),
* any runtime I/O error ⇒ disconnect, let the caller's reconnect probe
  recover (`server/.../main.py:198-200`).

Fault injection hooks (connect failures, hangs, read errors) give the test
suite the failure-path coverage the reference only ever exercised on a
workshop floor (SURVEY.md §4).

Raw value conventions match the wire: motor speeds are unsigned 16-bit with
negative wrap (`sign_extend_16bit` undoes it), prox.horizontal is 7 ints
(front 0-4, rear 5-6), leds.top is [r, g, b] 0-32.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from jax_mapping.config import RobotConfig

# Thymio variable names used by the reference.
MOTOR_LEFT_TARGET = "motor.left.target"
MOTOR_RIGHT_TARGET = "motor.right.target"
MOTOR_LEFT_SPEED = "motor.left.speed"
MOTOR_RIGHT_SPEED = "motor.right.speed"
PROX_HORIZONTAL = "prox.horizontal"
LEDS_TOP = "leds.top"


class DriverError(RuntimeError):
    """Raised on I/O against a dead link (the exception path the brain's
    catch-all turns into a reconnect, `server/.../main.py:198-200`)."""


class _VarView:
    """Dict-style view of one robot's variables (thymiodirect's surface)."""

    def __init__(self, driver: "SimulatedThymioDriver", node_id: int):
        self._driver = driver
        self._node_id = node_id

    def __getitem__(self, name: str):
        return self._driver._read_var(self._node_id, name)

    def __setitem__(self, name: str, value) -> None:
        self._driver._write_var(self._node_id, name, value)


class SimulatedThymioDriver:
    """Simulated fleet behind the thymiodirect access surface.

    Holds host-side mirrors of wheel targets/speeds/prox/LEDs for R robots;
    the owner (a simulation node) refreshes speeds and prox each physics
    tick via `ingest_state`. Connection lifecycle and fault injection mimic
    serial-dongle behavior.
    """

    def __init__(self, n_robots: int = 1,
                 fail_connect_times: int = 0,
                 hang_connect_times: int = 0,
                 fail_reads_after: Optional[int] = None):
        self.n_robots = n_robots
        self.connected = False
        self.fail_connect_times = fail_connect_times
        self.hang_connect_times = hang_connect_times
        self.fail_reads_after = fail_reads_after
        self.n_connect_calls = 0
        self._n_reads = 0
        self._lock = threading.Lock()
        self._targets = np.zeros((n_robots, 2), np.int32)
        self._speeds_raw = np.zeros((n_robots, 2), np.uint16)
        self._prox = np.zeros((n_robots, 7), np.int32)
        self._leds = np.zeros((n_robots, 3), np.int32)
        # Per-robot kill switch (resilience/faultplan.py "kill_robot"):
        # a disabled robot's motor-target writes are forced to 0 — the
        # firmware-watchdog behavior of a robot whose link died.
        self._enabled = np.ones(n_robots, bool)

    # -- thymiodirect-shaped surface ---------------------------------------

    def connect(self) -> None:
        """May fail or hang per injection settings (the real library can do
        both, which is why the pi variant wraps it in a thread+join)."""
        self.n_connect_calls += 1
        if self.hang_connect_times > 0:
            self.hang_connect_times -= 1
            time.sleep(3600.0)       # caller's join(timeout) abandons us
        if self.fail_connect_times > 0:
            self.fail_connect_times -= 1
            raise DriverError("dongle did not answer")
        self.connected = True

    def disconnect(self) -> None:
        self.connected = False

    def first_node(self) -> int:
        """The reference grabs the first node id (`th.first_node()` pattern,
        `server/.../main.py:66-67`). Sim node ids are 0..R-1."""
        if not self.connected:
            raise DriverError("not connected")
        return 0

    def nodes(self) -> List[int]:
        if not self.connected:
            raise DriverError("not connected")
        return list(range(self.n_robots))

    def __getitem__(self, node_id: int) -> _VarView:
        return _VarView(self, node_id)

    # -- simulation-side state exchange ------------------------------------

    def ingest_state(self, wheel_speeds: np.ndarray,
                     prox: np.ndarray) -> None:
        """Physics tick: set measured wheel speeds (float thymio units,
        (R, 2)) and prox readings ((R, >=5) ints). Speeds are stored the way
        the wire stores them — wrapped unsigned 16-bit — so the brain's
        sign-extension path is exercised for real."""
        with self._lock:
            s = np.round(np.asarray(wheel_speeds)).astype(np.int32)
            self._speeds_raw = (s & 0xFFFF).astype(np.uint16)
            p = np.asarray(prox, np.int32)
            self._prox[:, :p.shape[1]] = p

    def set_robot_enabled(self, node_id: int, enabled: bool) -> None:
        """Kill / revive one robot (fault injection): while disabled its
        wheel targets pin to 0 regardless of what the brain writes."""
        with self._lock:
            self._enabled[node_id] = enabled
            if not enabled:
                self._targets[node_id] = 0

    def targets(self) -> np.ndarray:
        with self._lock:
            return self._targets.copy()

    def leds(self) -> np.ndarray:
        with self._lock:
            return self._leds.copy()

    # -- variable access (driver-internal) ---------------------------------

    def _check_io(self) -> None:
        if not self.connected:
            raise DriverError("link down")
        if self.fail_reads_after is not None \
                and self._n_reads >= self.fail_reads_after:
            self.connected = False
            raise DriverError("serial timeout")

    def _read_var(self, node_id: int, name: str):
        self._check_io()
        self._n_reads += 1
        with self._lock:
            if name == MOTOR_LEFT_SPEED:
                return int(self._speeds_raw[node_id, 0])
            if name == MOTOR_RIGHT_SPEED:
                return int(self._speeds_raw[node_id, 1])
            if name == PROX_HORIZONTAL:
                return self._prox[node_id].tolist()
            if name == MOTOR_LEFT_TARGET:
                return int(self._targets[node_id, 0])
            if name == MOTOR_RIGHT_TARGET:
                return int(self._targets[node_id, 1])
            if name == LEDS_TOP:
                return self._leds[node_id].tolist()
        raise KeyError(name)

    def _write_var(self, node_id: int, name: str, value) -> None:
        self._check_io()
        with self._lock:
            if name == MOTOR_LEFT_TARGET:
                self._targets[node_id, 0] = \
                    int(value) if self._enabled[node_id] else 0
            elif name == MOTOR_RIGHT_TARGET:
                self._targets[node_id, 1] = \
                    int(value) if self._enabled[node_id] else 0
            elif name == LEDS_TOP:
                self._leds[node_id] = np.asarray(value, np.int32)
            else:
                raise KeyError(name)


def connect_with_retries(driver, max_retries: int = 3,
                         timeout_s: float = 3.0,
                         smoke_test: bool = True,
                         log: Callable[[str], None] = lambda s: None) -> bool:
    """The pi variant's robust connect (`pi/src/.../main.py:56-64,97-157`):

    up to `max_retries` attempts; each runs `driver.connect()` on a worker
    thread and abandons it after `timeout_s` (the library has no timeout
    argument); on success, a smoke test reads a variable and writes the
    idle LED. Returns True on success, False ⇒ caller enters offline mode.
    """
    for attempt in range(1, max_retries + 1):
        log(f"thymio connect attempt {attempt}/{max_retries}")
        result: Dict[str, Optional[BaseException]] = {"err": None}
        done = threading.Event()

        def work():
            try:
                driver.connect()
            except BaseException as e:          # noqa: BLE001
                result["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=work, daemon=True)
        t.start()
        if not done.wait(timeout=timeout_s):
            log("connect timed out; abandoning worker")
            continue
        if result["err"] is not None:
            log(f"connect failed: {result['err']}")
            continue
        if smoke_test:
            try:
                node = driver.first_node()
                driver[node][MOTOR_LEFT_SPEED]          # readable?
                driver[node][LEDS_TOP] = [0, 32, 0]     # idle green
            except Exception as e:                      # noqa: BLE001
                log(f"smoke test failed: {e}")
                try:
                    driver.disconnect()
                except Exception:                       # noqa: BLE001
                    pass
                continue
        log("thymio connected")
        return True
    log("all connect attempts failed; entering offline mode")
    return False
