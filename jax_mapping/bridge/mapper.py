"""Mapper node: the slam_toolbox replacement in the node graph.

Occupies exactly the box SURVEY.md §3.4 describes — subscribe `/scan`
(Best-Effort, report.pdf §V.A) + `/odom`, run gate → correlative match →
pose-graph insert → loop closure → grid fusion ON DEVICE (`models.slam`),
publish `/map` every `map_publish_period_s` (5 s, `slam_config.yaml:25`),
`/frontiers` each tick, and the `map->odom` correction TF
(role of slam_toolbox per SURVEY.md §1 L2).

Multi-robot memory architecture (round-3 verdict weak #4): ONE shared
grid for the whole fleet — the `models/fleet.py` design and the
reference's own (a single slam_toolbox fuses every robot's scans into one
map, `pc_server.launch.py:14-19`). Per-robot SlamStates carry poses,
graphs and scan rings; their `.grid` fields all ALIAS the shared array
(JAX arrays are immutable, aliasing is free), and each robot's device
step reads and writes the shared map in turn — so robots match against
each other's walls, as in the reference. After any loop closure the
shared map is re-fused from EVERY robot's key-scan ring (the closure's
own repair only re-fused the closing robot's ring).

QoS fidelity: the scan subscription is Best-Effort with a bounded queue, and
the batcher pairs each scan with the freshest odometry at or before its
stamp — tolerant of drops and reordering by construction (SURVEY.md §7
"hard parts").
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, Optional

import numpy as np

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.brain import robot_ns
from jax_mapping.bridge.messages import (
    FrontierArray, Header, LaserScan, Odometry, TransformStamped,
    occupancy_from_logodds,
)
from jax_mapping.bridge.node import Node
from jax_mapping.bridge.odom_pairing import OdomPairer
from jax_mapping.bridge.qos import QoSProfile, qos_map, qos_sensor_data
from jax_mapping.bridge.tf import TfTree
from jax_mapping.config import SlamConfig
from jax_mapping.ops.odometry import twist_to_wheel_units
from jax_mapping.utils import global_metrics as M


class MapperNode(Node):
    """Device-resident SLAM behind the reference's topic contract."""

    def __init__(self, cfg: SlamConfig, bus: Bus,
                 tf: Optional[TfTree] = None, n_robots: int = 1,
                 tick_period_s: Optional[float] = None, health=None,
                 recovery=None, pipeline=None, slo=None,
                 spill_dir: Optional[str] = None):
        super().__init__("jax_mapper", bus, tf)
        import jax.numpy as jnp

        from jax_mapping.models import slam as S
        from jax_mapping.ops import frontier as F
        from jax_mapping.ops import grid as G

        #: Bounded-memory world (world/store.py) or None. When
        #: `cfg.world.windowed`, the node's DEVICE config shrinks to
        #: the derived window-sized SlamConfig — `slam_step` is
        #: config-static, so the matcher, pyramids, graph, loop
        #: closure and frontier all run at window scale unchanged —
        #: while `self.full_cfg` keeps the logical-extent original
        #: (serving tile lattice, /map metadata). Poses live in the
        #: robocentric WINDOW frame; `_maybe_shift_window` translates
        #: them on each tile-aligned shift and every publish boundary
        #: adds `world.offset_xy()` back (world = window + offset).
        #: None = bit-exact pre-windowed behavior (every use gates).
        self.full_cfg = cfg
        self.world = None
        if cfg.world.windowed:
            from jax_mapping.world.store import WorldStore
            self.world = WorldStore(cfg, spill_dir=spill_dir)
            cfg = self.world.cfg
        self.cfg = cfg
        self.n_robots = n_robots
        self._S, self._F, self._G, self._jnp = S, F, G, jnp

        #: Causal tracing (obs/): the bus's Tracer or None. Set BEFORE
        #: any subscription exists — `_scan_cb` captures the delivery
        #: context per scan so a fused scan's span chain reaches back
        #: to its sim publish. None = pre-obs behavior exactly.
        self._tracer = getattr(bus, "tracer", None)
        #: Pipeline latency ledger (obs/pipeline.py) or None: stamps
        #: each revision's scan-enqueued → installed → notified
        #: waypoints (the serving tier stamps encode/deliver). None =
        #: pre-obs behavior exactly — not a single time call added.
        self._pipeline = pipeline
        #: Freshness SLO engine (obs/slo.py) or None: evaluated once
        #: per tick on the deterministic step clock, AFTER the tick
        #: body (the tick's own duration feeds the deadline
        #: objective).
        self._slo = slo
        self._tick_no = 0
        #: Per-robot monotone fuse-span keys (deterministic — see
        #: _emit_fuse_spans).
        self._fuse_no = [0] * n_robots
        self._state_lock = threading.Lock()
        # One grid for the fleet; every state's .grid aliases it.
        self.shared_grid = G.empty_grid(cfg.grid)
        self.states = [
            S.init_state(cfg)._replace(grid=self.shared_grid)
            for _ in range(n_robots)]
        #: Per-robot state generation: bumped whenever a robot's state is
        #: replaced out-of-band (/initialpose, restore). The shared-grid
        #: identity check in _finish_step cannot see an /initialpose
        #: reset (it keeps the same grid object), so in-flight steps also
        #: compare this counter before installing their result.
        self._state_gen = [0] * n_robots
        #: Per-robot (estimated pose, paired odom pose) at the last
        #: INSTALLED step — the basis of the map->odom correction the 3D
        #: mapper consumes (depth_anchor); None until a step installs.
        self._correction = [None] * n_robots
        #: Imported map prior (seed_map_prior). Kept so loop-closure ring
        #: re-fusions — which rebuild from an EMPTY grid — can backfill
        #: the cells no live key scan covers; without this the first
        #: closure silently erases the imported map.
        self._map_prior = None
        #: Optional callable returning the log-odds grid FRONTIER
        #: ASSIGNMENT should run on (launch wires the planner's
        #: voxel-overlaid planning basis); None = the shared 2D map.
        #: Preferred signature: provider(lo, revision) — the planner
        #: overlays THIS node's consistent snapshot instead of taking
        #: its own (the pose/grid pairing stays tear-free); legacy
        #: no-arg providers still work.
        self.frontier_grid_provider = None
        #: Companion key callable: the provider output's NON-tile-
        #: tracked ingredient (the voxel overlay's fusion key). The
        #: incremental frontier pipeline invalidates every cached tile
        #: when it changes; a wired provider WITHOUT a key provider
        #: forces a full recompute per publish (no way to know the
        #: overlay held still).
        self.frontier_grid_key_provider = None
        #: Incremental publish pipeline (ops/frontier_incremental.py):
        #: built lazily on the first publish with revision tracking
        #: available; a geometry rejection (ValueError) latches the
        #: full-recompute fallback so the publish path never retries a
        #: known-bad construction.
        self._frontier_pipeline = None
        self._frontier_pipeline_failed = False
        self._pairer = OdomPairer(n_robots)
        #: Per-robot covariance diag of the last ACCEPTED match
        #: (models.slam SlamDiag.cov) — published with /pose, the
        #: PoseWithCovariance slam_toolbox serves. None until a match.
        self._last_cov = [None] * n_robots
        #: Odometry-scale calibration accumulators (see _finish_step):
        #: per-robot EWMA sums of matched straight-motion SLAM vs
        #: odometry displacement. Decayed so the estimate TRACKS the
        #: battery/slip drift it exists to measure (a lifetime average
        #: would report the coeff of an hour ago); effective window
        #: ~1/(1-decay) samples.
        self._calib_decay = 0.995
        self._calib_odo = [0.0] * n_robots
        self._calib_slam = [0.0] * n_robots
        self._calib_n = [0] * n_robots       # lifetime sample count
        #: Previous installed step's matched flag, per robot: a
        #: re-convergence snap after a dead-reckoned stretch lands the
        #: ACCUMULATED correction in one step's d_slam — only
        #: matched-after-matched steps are clean samples.
        self._prev_matched = [False] * n_robots
        #: Per-robot queued (scan, TraceContext|None) pairs (see
        #: _scan_cb; the context is None whenever tracing is off).
        self._scan_q: List[List[tuple]] = [[] for _ in range(n_robots)]
        self._prev_paired: List[Optional[Odometry]] = [None] * n_robots
        #: Shared degraded-mode registry (resilience/health.py) — read
        #: for the dead-robot frontier reassignment; None = pre-
        #: resilience behavior.
        self._health = health
        #: Estimator guardrails (recovery/manager.py) — watchdog feed,
        #: quarantine + relocalization, frontier blacklist. None =
        #: pre-guardrail behavior exactly (every use gates on it).
        self._recovery = recovery
        #: Per-robot quarantined (scan, odom) evidence while diverged —
        #: BUFFERED, never fused (the paired poses are exactly what
        #: diverged); bounded by RecoveryConfig.quarantine_cap.
        self._quarantine: List[List] = [[] for _ in range(n_robots)]
        self.n_scans_quarantined = 0
        self.n_quarantine_overflow = 0
        self.n_relocalizations = 0
        #: Stamp of the newest scan accepted for fusion, per robot: a
        #: scan OLDER than this arrived late (cross-tick reorder, a
        #: healed partition flushing a stale queue) and is rejected —
        #: fusing it would smear old evidence at a newer pose.
        self._last_accepted_stamp = [-float("inf")] * n_robots
        #: Serving (serving/tiles.py): monotonic map revision bumped on
        #: every grid-content mutation (install, closure re-fuse,
        #: restore, prior seed) + a boolean dirty-tile mask marking the
        #: fixed-size tiles each mutation's patch extents touched — the
        #: conservative superset the tile store's on-device hash diff
        #: validates against. enabled=False keeps both untracked (exact
        #: pre-serving behavior; every use gates on the flag).
        self._serving_enabled = cfg.serving.enabled
        self.map_revision = 0
        #: Restart epoch (serving/client.py): bumped by the supervisor's
        #: mapper restarter on the REPLACEMENT node, stamped into every
        #: /tiles response + ETag. A resume from checkpoint legitimately
        #: re-serves an older `map_revision`; the epoch tells delta
        #: clients to drop their cache and resync full instead of
        #: raising a revision-regression protocol error. Set once before
        #: the node serves (launch.restart_mapper), read lock-free.
        self.restart_epoch = 0
        #: Map-healing clock (DecayConfig): mapper ticks since boot; a
        #: decay pass runs every `decay.every_n_ticks` ticks when
        #: enabled. Tick-thread-only state (single writer, the
        #: `_prev_paired` discipline). enabled=False never consults it.
        self._decay_ticks = 0
        self.n_decay_passes = 0
        #: Leaf lock for the dirty-tile mask: markers run while holding
        #: `_state_lock` (install atomicity), the snapshot consumer
        #: nests it the same way — one acquisition order, no cycle.
        self._dirty_lock = threading.Lock()
        self._dirty_tiles: Optional[np.ndarray] = None
        #: Per-tile LAST-DIRTY revision (same tile grid as the serving
        #: mask, never cleared): `region_revision` reduces it over a cell
        #: rectangle — the pruned matcher's pyramid-cache invalidation
        #: key (ops/pyramid.PyramidCache). Guarded by `_dirty_lock` with
        #: the mask; None when serving (and thus revision tracking) is
        #: off.
        self._tile_rev: Optional[np.ndarray] = None
        if self._serving_enabled:
            # The tile lattice is LOGICAL-extent: in windowed mode the
            # dirty/revision maps cover the whole addressable world
            # (markers translate window-local coordinates through the
            # store's origin), so serving and the pyramid caches see
            # one consistent lattice however the window moves.
            if self.full_cfg.grid.size_cells % cfg.serving.tile_cells:
                raise ValueError(
                    f"ServingConfig.tile_cells={cfg.serving.tile_cells} "
                    f"does not divide grid.size_cells="
                    f"{self.full_cfg.grid.size_cells}")
            nt = self.full_cfg.grid.size_cells // cfg.serving.tile_cells
            self._dirty_tiles = np.zeros((nt, nt), bool)
            self._tile_rev = np.zeros((nt, nt), np.int64)
        #: Last key-scan match work accounting per robot (SlamDiag
        #: match_candidates/match_prune_ratio) — /metrics gauges.
        self._match_candidates = [0] * n_robots
        self._match_prune_ratio = [0.0] * n_robots
        #: Revision listeners (the serving event channel): called with
        #: the new revision from the tick thread, OUTSIDE every mapper
        #: lock — fan-out must never run under _state_lock (lint B2).
        self._revision_listeners: List = []
        self._last_notified_revision = 0
        self._last_recorded_revision = 0
        self.n_scans_fused = 0
        self.n_scans_dropped_unpaired = 0
        self.n_scans_rejected_stale = 0
        self.n_windows_rejected_low_agreement = 0
        self.n_loops_closed = 0
        self.n_windows_fused = 0
        self.n_low_agreement_windows = 0

        self.map_pub = self.create_publisher("/map", qos_map)
        self.map_updates_pub = self.create_publisher("/map_updates")
        self.frontiers_pub = self.create_publisher("/frontiers")
        self.pose_pub = self.create_publisher("/pose")
        for i in range(n_robots):
            ns = robot_ns(i, n_robots)
            self.create_subscription(
                f"{ns}scan", functools.partial(self._scan_cb, i),
                qos_sensor_data)
            self.create_subscription(
                f"{ns}odom", functools.partial(self._odom_cb, i),
                QoSProfile(depth=50))

        # RViz SetInitialPose tool (via the rclpy adapter): relocalize
        # robot 0's SLAM estimate — slam_toolbox's pose-initialization
        # capability, applied to the reference's single-robot convention.
        self.create_subscription("/initialpose", self._initialpose_cb)

        period = tick_period_s if tick_period_s is not None \
            else 1.0 / cfg.robot.control_rate_hz
        self.graph_pub = self.create_publisher("/graph")
        # Heartbeat for the Supervisor (supervisor restarts THIS node
        # from checkpoint when beats stop).
        from jax_mapping.resilience.supervisor import Heartbeater
        self._heartbeater = Heartbeater(self)
        self.create_timer(period, self.tick)
        self.create_timer(cfg.map_publish_period_s, self.publish_map)
        # Graph viz rides the slow map cadence: nodes move only on key
        # scans/closures, and RViz redraws the whole MarkerArray.
        self.create_timer(cfg.map_publish_period_s, self.publish_graph)
        self._last_map_stamp = 0.0

    # -- callbacks ----------------------------------------------------------

    def _initialpose_cb(self, msg) -> None:
        pose = [float(msg.x), float(msg.y), float(msg.theta)]
        if self.world is not None:
            # Asserted poses arrive in WORLD coordinates; the chain
            # lives in the robocentric window frame.
            off = self.world.offset_xy()
            pose[0] -= float(off[0])
            pose[1] -= float(off[1])
        self.reset_robot_pose(0, pose)
        M.counters.inc("mapper.initialpose_resets")

    def reset_robot_pose(self, i: int, pose) -> None:
        """Re-anchor robot i's chain at an asserted pose, keeping the
        map — slam_toolbox's localization-reset semantics. ONE
        implementation for both assertion ingresses: the RViz
        SetInitialPose tool (`_initialpose_cb`, robot 0) and the
        recovery relocalizer's verified re-anchor (any robot).

        An asserted pose starts a FRESH chain: keeping the old graph
        would leave an odometry edge spanning the teleport, and the
        next loop optimisation would drag the estimate back toward the
        pre-reset frame (silently undoing the assertion).
        fresh.last_key_pose forces an immediate key scan, promptly
        re-anchoring graph node 0 at the asserted pose. The map is
        kept: the fresh state aliases the shared grid."""
        jnp = self._jnp
        pose = jnp.asarray(np.asarray(pose, np.float32))
        with self._state_lock:
            fresh = self._S.init_state(self.cfg, pose0=pose)
            self.states[i] = fresh._replace(grid=self.shared_grid)
            self._state_gen[i] += 1
            self._prev_paired[i] = None
            self._prev_matched[i] = False
            self._correction[i] = None

    # -- serving surface (serving/tiles.py) ----------------------------------

    def _mark_dirty_patch(self, xy) -> None:
        """Mark the serving tiles a fusion patch centred near world
        point `xy` may have touched (caller holds `_state_lock`).

        Derived from the patch geometry the install actually used
        (`ops/grid.patch_origin`): the patch spans `patch_cells` around
        the pose, and origin alignment can shift it up to align/2 cells
        — pad by the alignment plus a small slack so window fallbacks
        (per-scan patches at poses a few cells apart) stay covered.
        Deliberately conservative: the tile store's on-device hash diff
        prunes false positives; a false NEGATIVE here only shows up in
        the store's `n_hint_missed` telemetry (the hash, not this mask,
        decides what re-encodes)."""
        if self._dirty_tiles is None:
            return
        g = self.cfg.grid
        half = (g.patch_cells / 2.0
                + max(g.align_rows, g.align_cols) / 2.0 + 8.0)
        col = (xy[0] - g.origin_m[0]) / g.resolution_m
        row = (xy[1] - g.origin_m[1]) / g.resolution_m
        t = self.cfg.serving.tile_cells
        nt = self._dirty_tiles.shape[0]
        # Window-local coordinates map to the logical lattice through
        # the store's origin (identity zero when not windowed).
        off_r, off_c = (0, 0) if self.world is None \
            else self.world.origin_tile
        r0 = min(nt - 1, max(0, int((row - half) // t) + off_r))
        r1 = min(nt - 1, max(0, int((row + half) // t) + off_r))
        c0 = min(nt - 1, max(0, int((col - half) // t) + off_c))
        c1 = min(nt - 1, max(0, int((col + half) // t) + off_c))
        with self._dirty_lock:
            self._dirty_tiles[r0:r1 + 1, c0:c1 + 1] = True
            self._tile_rev[r0:r1 + 1, c0:c1 + 1] = self.map_revision

    def _mark_dirty_box(self, box) -> None:
        """Mark an inclusive [tr0, tr1] x [tc0, tc1] serving-tile box
        dirty (caller holds `_state_lock`; `box` is host ints — the
        fetch happened outside every lock). The fused-fusion feed
        (`ops/fuse_kernel.touched_tile_box`): the box is DEVICE-computed
        from the exact `patch_origin` extents the install's fusion used,
        so the hint is tighter than `_mark_dirty_patch`'s half-extent
        padding while staying a conservative superset — the tile store's
        hash diff remains the re-encode criterion either way."""
        if self._dirty_tiles is None:
            return
        tr0, tr1, tc0, tc1 = box
        if self.world is not None:
            # The box is window-tile coordinates (device-computed on
            # the window grid); translate to the logical lattice.
            off_r, off_c = self.world.origin_tile
            tr0, tr1 = tr0 + off_r, tr1 + off_r
            tc0, tc1 = tc0 + off_c, tc1 + off_c
        with self._dirty_lock:
            self._dirty_tiles[tr0:tr1 + 1, tc0:tc1 + 1] = True
            self._tile_rev[tr0:tr1 + 1, tc0:tc1 + 1] = self.map_revision

    def _touched_box(self, i: int, state, travel_cells: int):
        """Device-computed touched-tile bounds for an install of robot
        i's step ending at `state.pose` — None when the fused path or
        serving is off (callers then fall back to the host marker).
        Covers the step's pose ENDPOINTS (previous installed estimate +
        the new one) with the exact patch geometry the fusion used,
        padded by `travel_cells` — the window's odometric path-length
        bound, so interior poses (and the per-scan-patch window
        fallback) stay covered however the robot looped. Runs OUTSIDE
        `_state_lock` and returns host ints (four scalar fetches, the
        bool(diag.matched) fetch discipline — never a device wait under
        a lock); `_correction` is tick-thread-only state (the
        `_prev_paired` single-writer discipline)."""
        if not self._serving_enabled or not self.cfg.grid.fused_fusion:
            return None
        from jax_mapping.ops import fuse_kernel as FK
        jnp = self._jnp
        new_xy = state.pose[:2]
        prev = self._correction[i]
        prev_xy = new_xy if prev is None else jnp.asarray(prev[0][:2])
        pts = jnp.stack([prev_xy, new_xy]).astype(jnp.float32)
        box = FK.touched_tile_box(
            self.cfg.grid, self.cfg.serving.tile_cells, pts,
            jnp.int32(travel_cells))
        return tuple(int(v) for v in box)

    def _mark_dirty_all(self) -> None:
        """Whole-map mutation (closure ring re-fuse, restore, prior
        seed): every tile is suspect. Caller holds `_state_lock`."""
        if self._dirty_tiles is not None:
            with self._dirty_lock:
                self._dirty_tiles[:] = True
                self._tile_rev[:] = self.map_revision

    def region_revision(self, row0: int, col0: int,
                        span_cells: int) -> Optional[int]:
        """Newest `map_revision` whose mutation marked any serving tile
        intersecting the cell rectangle [row0, row0+span) x
        [col0, col0+span) — the pyramid cache's freshness key: equal
        revision = nothing touched the region since the pyramid was
        built. None when revision tracking is off (serving disabled);
        callers must then rebuild."""
        if self._tile_rev is None:
            return None
        t = self.cfg.serving.tile_cells
        nt = self._tile_rev.shape[0]
        # Callers pass window-local cell coordinates (the pyramids are
        # built over the device grid); translate through the window
        # origin onto the logical lattice (identity when not windowed).
        off_r, off_c = (0, 0) if self.world is None \
            else self.world.origin_tile
        r0 = min(nt - 1, max(0, row0 // t + off_r))
        r1 = min(nt - 1, max(0, (row0 + span_cells - 1) // t + off_r))
        c0 = min(nt - 1, max(0, col0 // t + off_c))
        c1 = min(nt - 1, max(0, (col0 + span_cells - 1) // t + off_c))
        with self._dirty_lock:
            return int(self._tile_rev[r0:r1 + 1, c0:c1 + 1].max())

    def serving_revision(self) -> int:
        """Current map revision — lock-free read (the /status counter
        convention: stale-by-one beats blocking behind a fusion)."""
        return self.map_revision

    def serving_snapshot(self):
        """(revision, shared grid, dirty-tile hint) — the tile store's
        refresh source. The hint mask is CONSUMED (copied and cleared)
        atomically with the grid snapshot: marks recorded before this
        moment are by construction contained in the returned grid, and
        marks landing after it accumulate for the next refresh."""
        with self._state_lock:
            rev = self.map_revision
            grid = self.shared_grid
            hint = None
            if self._dirty_tiles is not None:
                with self._dirty_lock:
                    hint = self._dirty_tiles.copy()
                    self._dirty_tiles[:] = False
        return rev, grid, hint

    def world_status(self):
        """Bounded-memory world introspection for /status.world and the
        jax_mapping_world_* metrics; None when not windowed (the knob-off
        doctrine: no new status surface unless the store exists)."""
        if self.world is None:
            return None
        body = self.world.status()
        off = self.world.offset_xy()
        body["offset_m"] = [float(off[0]), float(off[1])]
        return body

    def destroy(self) -> None:
        super().destroy()
        if self.world is not None:
            # Release the spill file handle: a staged restart reopens
            # the SAME spill file from the replacement node, and two
            # live writers would interleave (= corrupt) frames.
            self.world.close()

    def add_revision_listener(self, fn) -> None:
        """Register fn(revision): called from the tick thread after the
        tick's installs, outside every mapper lock (serving event
        fan-out)."""
        self._revision_listeners.append(fn)

    def _notify_revision_listeners(self) -> None:
        """Tick-thread fan-out of revision advances — deliberately
        outside `_state_lock` (lint B2: no foreign code under a lock);
        a listener landing one tick late is fine, a deadlock is not.
        The flight recorder logs the advance at the same coalesced
        per-tick granularity (obs/recorder.py: the map_revision bump as
        a structured transition, not just a counter)."""
        if not self._serving_enabled:
            return
        rev = self.map_revision
        if self._pipeline is not None and rev > 0:
            # Notify waypoint: the revision is now fanned to listeners
            # (the /map-events nudge) — idempotent for already-marked
            # revisions, so the unconditional call is cheap.
            self._pipeline.notified(rev)
        if rev != self._last_recorded_revision:
            self._last_recorded_revision = rev
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("map_revision", revision=rev)
        # Listener stamp advances ONLY on an actual delivery: during a
        # supervisor restart the new mapper ticks before rebind_mapper
        # re-registers the serving listener, and a stamp taken in that
        # window would swallow the first post-registration notify (the
        # /map-events nudge for that revision would never fire).
        if rev == self._last_notified_revision or \
                not self._revision_listeners:
            return
        self._last_notified_revision = rev
        for fn in list(self._revision_listeners):
            fn(rev)

    # -- checkpoint surface --------------------------------------------------

    def snapshot_states(self) -> List:
        """Consistent checkpoint snapshot of the per-robot SLAM states.

        All states alias ONE shared grid; serializing it R times would
        fetch and compress 64 MB x R of identical data per /save
        (production 8-robot config: ~0.5 GB). The snapshot keeps the
        shared grid on robot 0 and gives the rest host-side zero grids —
        same pytree structure (load templates match), near-zero
        compressed size, and `restore_states`'s dominant-evidence merge
        reconstructs the shared alias exactly on load."""
        with self._state_lock:
            states = list(self.states)
            shared = self.shared_grid
        if len(states) == 1:
            return states
        zero = np.zeros((self.cfg.grid.size_cells,) * 2, np.float32)
        return [states[0]._replace(grid=shared)] + \
            [st._replace(grid=zero) for st in states[1:]]

    def restore_states(self, states, anchor_poses=None,
                       map_prior=None) -> None:
        """Swap in checkpointed SLAM states and reset odometry pairing.

        map_prior: the checkpoint's imported-map prior (its .prior
        sidecar), or None — which CLEARS any live prior: the checkpoint
        is now the source of truth, and a stale prior from the previous
        session would backfill a different environment's walls at the
        next loop closure.

        Both resume paths (HTTP /load, demo --resume) go through here so
        the pairing reset can't be forgotten at one call site: without it
        the first post-restore odometry pair would integrate the jump
        between the stale and live odom frames into the pose estimate.

        anchor_poses: optional (R, 3) rows. When given, robot i's chain is
        RE-ANCHORED at anchor_poses[i] — fresh graph from that pose,
        inherited grid (the `_initialpose_cb` localization-reset
        semantics) — for resumes where the physical robot no longer sits
        at the checkpointed pose (a relaunched sim respawns robots; scans
        fused at the stale endpoint pose would corrupt the inherited
        map). Omit it only when poses are still valid (a server restart
        with robots holding still).
        """
        if len(states) != len(self.states):
            raise ValueError(
                f"checkpoint has {len(states)} robot state(s), the stack "
                f"runs {len(self.states)}")
        jnp = self._jnp
        with self._state_lock:
            self._map_prior = (None if map_prior is None
                               else jnp.asarray(map_prior,
                                                dtype="float32"))
            self.states = list(states)
            # Rebuild the shared grid from the checkpoint: states saved by
            # this design all alias one grid (max-merge is then a no-op);
            # states from an older per-robot-grid checkpoint may diverge,
            # so merge conservatively by dominant evidence.
            g = self.states[0].grid
            for st in self.states[1:]:
                g = jnp.where(jnp.abs(st.grid) > jnp.abs(g), st.grid, g)
            self.shared_grid = g
            for i in range(len(self.states)):
                if anchor_poses is not None:
                    pose = jnp.asarray(anchor_poses[i], dtype="float32")
                    fresh = self._S.init_state(self.cfg, pose0=pose)
                    self.states[i] = fresh
                self.states[i] = self.states[i]._replace(
                    grid=self.shared_grid)
                self._state_gen[i] += 1
                self._prev_paired[i] = None
                self._prev_matched[i] = False
                self._correction[i] = None
            if self._serving_enabled:
                # A restore replaces the whole shared grid out-of-band.
                self.map_revision += 1
                self._mark_dirty_all()

    def map_prior(self):
        """The live imported-map prior (for checkpoint sidecars), or
        None."""
        with self._state_lock:
            return self._map_prior

    def seed_map_prior(self, prior_logodds) -> None:
        """Install an imported map (io/rosmap.load_map -> logodds_prior)
        as the fleet's shared grid — localization-on-a-known-map
        bootstrapping (slam_toolbox's map-start / map_server role).

        The prior REPLACES the grid through a fresh array, so
        _finish_step's shared-grid identity check drops any in-flight
        step fused from the pre-seed grid; per-robot generations bump for
        the /initialpose-style guards. Graphs and poses are untouched:
        robots keep localizing, now against the imported walls.
        """
        jnp = self._jnp
        if self.world is not None:
            raise ValueError(
                "map priors are not supported in windowed mode "
                "(world.windowed): a logical-extent prior exceeds the "
                "device window — import it unwindowed or grow the "
                "window to the prior's extent")
        g = self.cfg.grid
        prior = jnp.asarray(prior_logodds, dtype="float32")
        if prior.shape != (g.size_cells, g.size_cells):
            raise ValueError(
                f"map prior shape {prior.shape} != grid "
                f"({g.size_cells}, {g.size_cells}); resample the import "
                "to the running config first (io/rosmap.embed_in_grid)")
        with self._state_lock:
            self.shared_grid = prior
            self._map_prior = prior
            for i in range(len(self.states)):
                self.states[i] = self.states[i]._replace(
                    grid=self.shared_grid)
                self._state_gen[i] += 1
            if self._serving_enabled:
                self.map_revision += 1
                self._mark_dirty_all()

    # -- topic callbacks -----------------------------------------------------

    def _scan_cb(self, i: int, msg: LaserScan) -> None:
        # Queue entries are (scan, delivery TraceContext|None, enqueue
        # stamp|None) triples: the bus made the publish context current
        # for this callback, and capturing it HERE (not at tick time)
        # is what lets the fuse span of a scan that waited in the queue
        # still chain to the publish that produced it. The enqueue
        # stamp is the pipeline ledger's scan→served starting waypoint
        # (server monotonic — the queue wait is part of freshness);
        # None when no ledger is armed, so the disabled path adds not
        # even a clock read.
        ctx = self._tracer.current() if self._tracer is not None else None
        enq_t = time.perf_counter() if self._pipeline is not None \
            else None
        with self._state_lock:
            self._scan_q[i].append((msg, ctx, enq_t))

    def _odom_cb(self, i: int, msg: Odometry) -> None:
        with self._state_lock:
            self._pairer.push(i, msg)

    # -- pairing + device step ----------------------------------------------

    def _pair_odom(self, i: int, stamp: float) -> Optional[Odometry]:
        """Freshest odometry at or before `stamp` (drop/reorder tolerant;
        shared rule: bridge/odom_pairing.py)."""
        return self._pairer.pair(i, stamp)

    def _pad_ranges(self, scan: LaserScan) -> np.ndarray:
        sc = self.cfg.scan
        out = np.zeros(sc.padded_beams, np.float32)
        r = np.asarray(scan.ranges, np.float32)
        n = min(len(r), sc.n_beams)
        if n == sc.n_beams and len(r) != sc.n_beams:
            idx = np.linspace(0, len(r) - 1, sc.n_beams).round().astype(int)
            out[:sc.n_beams] = r[idx]
        else:
            out[:n] = r[:n]
        return out

    def _odom_motion(self, i: int, od: Odometry) -> tuple:
        """(wl, wr, dt): equivalent wheel speeds + REAL interval from the
        actual pose delta between consecutive paired odometry samples.

        The reference integrates with the true wall-clock dt
        (`server/.../main.py:90-115`); twist x fixed control-period dt
        would systematically under/over-integrate motion whenever scans
        arrive slower or faster than the control rate — guaranteed under
        the Best-Effort drops this node is designed for. Inverting the RK2
        midpoint model on the measured pose delta makes the device-side
        integration land exactly on the paired odometry pose.
        """
        import math
        prev = self._prev_paired[i]
        self._prev_paired[i] = od
        if prev is None or od.header.stamp <= prev.header.stamp:
            # Bootstrap, or the same/out-of-order sample paired again: no
            # new odometric evidence — integrate zero motion rather than
            # fabricating some from a stale twist.
            return 0.0, 0.0, 1.0 / self.cfg.robot.control_rate_hz
        dt = od.header.stamp - prev.header.stamp
        dth = math.atan2(math.sin(od.pose.theta - prev.pose.theta),
                         math.cos(od.pose.theta - prev.pose.theta))
        mid = prev.pose.theta + dth / 2.0         # RK2 midpoint heading
        dx = od.pose.x - prev.pose.x
        dy = od.pose.y - prev.pose.y
        v = (math.cos(mid) * dx + math.sin(mid) * dy) / dt
        w = dth / dt
        wl, wr = twist_to_wheel_units(self.cfg.robot, v, w)
        return float(wl), float(wr), dt

    def tick(self) -> None:
        """Drain queues, run the device SLAM step(s) per robot.

        Full windows of `fleet.batch_scans` queued scans go through
        `slam_step_window` (the shared-patch throughput path: one grid
        read-modify-write per window); the remainder steps scan-by-scan.

        Observability wrapper: the whole tick is one `mapper.tick`
        stage (latency histogram on /metrics) and — when tracing is on
        — one span, so everything the tick publishes (frontiers, pose,
        TF, heartbeat) chains under it unless a scan's own delivery
        context outranks it (`_emit_fuse_spans`).
        """
        self._tick_no += 1
        if self._pipeline is not None:
            self._pipeline.note_tick(self._tick_no)
        t0 = time.perf_counter()
        with M.stages.stage("mapper.tick"):
            if self._tracer is not None:
                with self._tracer.span("mapper.tick", key=self._tick_no):
                    self._tick_body()
            else:
                self._tick_body()
        if self._slo is not None:
            # Once per tick, AFTER the body: the step clock the burn
            # windows count on, with the just-finished tick's duration
            # (deadline objective) and the live revision counter
            # (staleness objective).
            self._slo.evaluate(self._tick_no,
                               tick_ms=(time.perf_counter() - t0) * 1e3,
                               map_revision=self.map_revision)

    def _tick_body(self) -> None:
        jnp = self._jnp
        self._maybe_shift_window()
        with self._state_lock:
            work: List[List] = [[] for _ in range(self.n_robots)]
            for i in range(self.n_robots):
                for scan, ctx, enq_t in sorted(
                        self._scan_q[i],
                        key=lambda e: e[0].header.stamp):
                    if self.cfg.resilience.enabled and \
                            scan.header.stamp < \
                            self._last_accepted_stamp[i]:
                        # Degraded-mode gate: a scan older than the
                        # newest already-fused one arrived LATE (cross-
                        # tick reorder / a healed partition flushing its
                        # backlog) — fusing it would smear stale
                        # evidence at the current pose chain.
                        self.n_scans_rejected_stale += 1
                        M.counters.inc("mapper.scans_rejected_stale")
                        continue
                    od = self._pair_odom(i, scan.header.stamp)
                    if od is None:
                        self.n_scans_dropped_unpaired += 1
                        M.counters.inc("mapper.scans_unpaired")
                        continue
                    # The watermark advances at INSTALL time
                    # (_finish_step), not here: evidence later rejected
                    # (low agreement) or dropped stale must not push it
                    # forward, or good reordered scans arriving next
                    # tick would be discarded against a watermark no
                    # fused evidence ever set.
                    work[i].append((scan, od, ctx, enq_t))
                self._scan_q[i].clear()

        for i, items in enumerate(work):
            if not items:
                continue
            if self._diverged(i):
                # Quarantine rung: this robot's estimator is declared
                # lost — its evidence buffers (never fuses) and every
                # tick attempts a wide-window relocalization with the
                # freshest scan. Only that one scan crosses to the
                # device (uploading the whole batch here would waste
                # N-1 rows of transfer every tick of the quarantine).
                self._quarantine_and_relocalize(
                    i, items, self._upload_scan_ranges(items[-1:])[0])
                continue
            # ONE host->device transfer per robot per tick: every queued
            # scan padded and stacked host-side, shipped together; the
            # window/single steps slice device rows off it. Per-scan
            # `jnp.asarray` paid N-1 extra round trips per tick at fleet
            # scale.
            ranges_dev = self._upload_scan_ranges(items)
            W = max(2, self.cfg.fleet.batch_scans)
            k = 0
            while k < len(items):
                if self._diverged(i):
                    # A step above just DECLARED divergence: the rest of
                    # this tick's queue is the same fault's evidence and
                    # quarantines with it (the watchdog's already-
                    # diverged early-exit would otherwise let later
                    # chunks fuse — the exact corruption quarantine
                    # exists to prevent).
                    self._quarantine_items(i, items[k:])
                    break
                if len(items) - k >= W:
                    self._step_window(i, items[k:k + W],
                                      ranges_dev[k:k + W])
                    k += W
                else:
                    self._step_single(i, items[k], ranges_dev[k])
                    k += 1
            if not self._diverged(i):
                # A step above may have DECLARED divergence: freezing
                # the correction TF at the last healthy step beats
                # re-asserting the diverged estimate.
                self._publish_correction(i, items[-1][0], items[-1][1])

        decayed = False
        # Localization mode tracks against a FROZEN map — healing it
        # away would erode the very prior the mode exists to keep.
        if self.cfg.decay.enabled and self.cfg.mode == "mapping":
            self._decay_ticks += 1
            if self._decay_ticks % max(1, self.cfg.decay.every_n_ticks) \
                    == 0:
                self._apply_decay()
                decayed = True

        if any(work) or decayed:
            self.publish_frontiers()
        self._notify_revision_listeners()
        self._heartbeater.beat(
            {"scans_fused": self.n_scans_fused,
             "rejected_stale": self.n_scans_rejected_stale,
             "loops_closed": self.n_loops_closed})

    def _maybe_shift_window(self) -> None:
        """Windowed-mode per-tick world maintenance (no-op otherwise):
        join last tick's disk prefetches into the window (the
        deterministic one-tick unknown-degrade), then recentre the
        window when a robot strays into the margin band.

        A shift is a whole-frame translation: the device grid rolls
        (one jitted dispatch, evicting/rehydrating through the store),
        and every pose-like leaf — state.pose, last_key_pose, the
        graph's pose rows, the install correction basis — translates
        by the shift delta. Graph EDGES are relative poses and scan
        rings are ranges-only, so the translation is the entire
        fix-up; generation bumps resync out-of-band consumers (voxel
        anchoring), and the revision bump + full dirty mark make
        serving, the frontier pipeline and the pyramid caches see the
        shift as an ordinary whole-map mutation. Runs on the tick
        thread BETWEEN steps — no in-flight step can race the swap
        (the `_apply_decay` discipline)."""
        if self.world is None:
            return
        jnp = self._jnp
        with self._state_lock:
            grid, n_rehydrated = self.world.poll_prefetch(
                self.shared_grid)
            if n_rehydrated:
                self.shared_grid = grid
                for j in range(self.n_robots):
                    self.states[j] = self.states[j]._replace(grid=grid)
                if self._serving_enabled:
                    self.map_revision += 1
                    self._mark_dirty_all()
            poses = [np.asarray(st.pose) for st in self.states]
            dr, dc = self.world.desired_shift(poses)
            if (dr, dc) == (0, 0):
                return
            with M.stages.stage("mapper.window_shift"):
                new_grid = self.world.shift(self.shared_grid, dr, dc)
            delta = self.world.shift_delta_m(dr, dc)
            shift3_np = np.array([delta[0], delta[1], 0.0], np.float32)
            shift3 = jnp.asarray(shift3_np)
            self.shared_grid = new_grid
            for j in range(self.n_robots):
                st = self.states[j]
                graph = st.graph._replace(
                    poses=st.graph.poses - shift3[None, :])
                self.states[j] = st._replace(
                    grid=new_grid,
                    pose=st.pose - shift3,
                    last_key_pose=st.last_key_pose - shift3,
                    graph=graph)
                self._state_gen[j] += 1
                if self._correction[j] is not None:
                    est, odo = self._correction[j]
                    self._correction[j] = (est - shift3_np, odo)
            if self._serving_enabled:
                self.map_revision += 1
                self._mark_dirty_all()
        M.counters.inc("mapper.window_shifts")
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("window_shift", dr=dr, dc=dc,
                               origin=list(self.world.origin_tile))

    def _apply_decay(self) -> None:
        """One map-healing pass (DecayConfig): shrink every cell's
        log-odds toward unknown and clamp to the evidence cap, in one
        jitted dispatch. Runs on the tick thread BETWEEN steps, so no
        in-flight step can race the grid swap; the revision bump + full
        dirty mark make serving, the incremental frontier pipeline and
        the pyramid caches all see the healed map as an ordinary
        revision advance (no special-case invalidation anywhere)."""
        d = self.cfg.decay
        with self._state_lock:
            g = self._G.decay_grid(self.shared_grid, d.factor,
                                   d.evidence_cap)
            self.shared_grid = g
            for j in range(self.n_robots):
                self.states[j] = self.states[j]._replace(grid=g)
            rev = None
            if self._serving_enabled:
                self.map_revision += 1
                rev = self.map_revision
                self._mark_dirty_all()
        if self._pipeline is not None and rev is not None:
            # A decay pass stamps its revision (served-revision ages
            # stay honest) but is NOT ingest: healing has no
            # acquisition, and advancing the ingest-stall clock here
            # would mask a scan-path outage from the SLO guard on
            # every decay cadence.
            self._pipeline.installed(rev, tick=self._tick_no,
                                     ingest=False)
        if self.world is not None:
            # Spilled tiles catch up lazily at rehydrate time (one
            # sequential clip(x*f) per missed pass — bit-exact with
            # the device's per-pass arithmetic).
            self.world.note_decay_pass()
        self.n_decay_passes += 1
        M.counters.inc("mapper.decay_passes")
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("decay_pass", n=self.n_decay_passes,
                               tick=self._decay_ticks)

    def _upload_scan_ranges(self, items: List):
        """One robot's queued scans, padded and stacked host-side, as a
        single (N, padded_beams) device transfer (tick's batched-upload
        contract)."""
        arr = np.stack([self._pad_ranges(it[0]) for it in items])
        M.counters.inc("mapper.scan_upload_batches")
        return self._jnp.asarray(arr)

    def _emit_fuse_spans(self, i: int, items: List) -> None:
        """One instant `mapper.fuse` span per INSTALLED scan, parented
        on the scan's bus-delivery context — the causal edge the
        trace-propagation gate asserts (sim publish -> queue -> fuse).
        A scan with no captured context (latched delivery, tracing
        armed mid-run) falls back to the ambient tick span. The span
        key is a per-robot monotone fuse counter, NOT the scan stamp:
        stamps are `time.monotonic()` wall clock, and a wall value in
        the id derivation would break the two-same-seed-runs
        stream-identity contract."""
        tr = self._tracer
        if tr is None:
            return
        for it in items:
            ctx = it[2] if len(it) > 2 else None
            self._fuse_no[i] += 1
            tr.emit("mapper.fuse", parent=ctx,
                    key=(i, self._fuse_no[i]))

    def _step_window(self, i: int, items: List, ranges_w) -> None:
        jnp = self._jnp
        W = len(items)
        # Snapshot generation BEFORE _odom_motion touches _prev_paired: a
        # restore landing between the two would otherwise pass the
        # _finish_step guard with _prev_paired holding a pre-restore
        # sample, and the next step would integrate the frame jump.
        with self._state_lock:
            base_grid = self.shared_grid
            base_gen = self._state_gen[i]
        motion = [self._odom_motion(i, it[1]) for it in items]
        wheels_w = np.asarray([[m[0], m[1]] for m in motion], np.float32)
        dts_w = np.asarray([m[2] for m in motion], np.float32)
        travel_cells = self._travel_cells(motion)
        state = self.states[i]._replace(grid=base_grid)
        with M.stages.stage("mapper.slam_step_window"):
            state, diag = self._S.slam_step_window(
                self.cfg, state, ranges_w,
                jnp.asarray(wheels_w), jnp.asarray(dts_w))
            matched = bool(diag.matched)
            closed = bool(diag.loop_closed)
            agreement = float(diag.window_agreement)
            if matched:
                self._last_cov[i] = np.asarray(diag.cov, np.float32)
            if bool(diag.key_added):
                self._note_match_stats(i, diag)
        if self.cfg.resilience.enabled and \
                agreement < self.cfg.resilience.window_agreement_reject:
            self._reject_low_agreement(i, items)
            return
        if self._observe_watchdog(i, matched, bool(diag.key_added),
                                  agreement, window=True):
            # The declaring step's own evidence is the first quarantined
            # window — by definition it is what pushed the score over.
            self._quarantine_items(i, items)
            return
        installed = self._finish_step(i, state, items[-1][1], W, matched,
                                      closed, base_grid, base_gen,
                                      items[-1][0].header.stamp,
                                      travel_cells=travel_cells,
                                      enq_t=self._oldest_enq(items))
        if not installed:
            return
        self._emit_fuse_spans(i, items)
        self.n_windows_fused += 1
        M.counters.inc("mapper.windows_fused")
        # Surface the leading scans' health (they fuse with no match
        # telemetry): a low-agreement window means evidence landed in
        # known-free space — misaligned odometry or a garbage burst.
        if agreement < 0.5:
            self.n_low_agreement_windows += 1
            M.counters.inc("mapper.low_agreement_windows")

    def _note_match_stats(self, i: int, diag) -> None:
        """Key-step matcher work gauges (SlamDiag match_candidates /
        match_prune_ratio -> /metrics); rides the fetches the stage
        timer already forces."""
        self._match_candidates[i] = int(diag.match_candidates)
        self._match_prune_ratio[i] = round(
            float(diag.match_prune_ratio), 4)

    def match_stats(self) -> dict:
        """Per-robot matcher work accounting for /status and /metrics
        (lock-free reads, the /status counter convention)."""
        return {"candidates": list(self._match_candidates),
                "prune_ratio": list(self._match_prune_ratio)}

    def _step_single(self, i: int, item: tuple, ranges) -> None:
        scan, od = item[0], item[1]
        jnp = self._jnp
        # Generation snapshot before the _odom_motion side effect — see
        # _step_window.
        with self._state_lock:
            base_grid = self.shared_grid
            base_gen = self._state_gen[i]
        wl, wr, dt = self._odom_motion(i, od)
        travel_cells = self._travel_cells([(wl, wr, dt)])
        state = self.states[i]._replace(grid=base_grid)
        with M.stages.stage("mapper.slam_step"):
            state, diag = self._S.slam_step(
                self.cfg, state, ranges,
                jnp.float32(wl), jnp.float32(wr), jnp.float32(dt))
            # Dispatch is async; the host-side fetches force execution
            # so the stage measures the device step, not the enqueue.
            matched = bool(diag.matched)
            closed = bool(diag.loop_closed)
            agreement = float(diag.window_agreement)
            if matched:
                self._last_cov[i] = np.asarray(diag.cov, np.float32)
            if bool(diag.key_added):
                self._note_match_stats(i, diag)
        if self.cfg.resilience.enabled and \
                agreement < self.cfg.resilience.window_agreement_reject:
            # Same do-no-harm floor as _step_window: the single-scan
            # cadence is the COMMON path, and a garbage scan must not
            # overwrite known-good map there either (slam_step computes
            # the pre-fusion agreement for key scans; skip/localization
            # steps report a neutral 1.0 — they add no evidence).
            # enabled=False restores pre-resilience fusion exactly (the
            # baseline-comparison contract of the flag).
            self._reject_low_agreement(i, [item])
            return
        if self._observe_watchdog(i, matched, bool(diag.key_added),
                                  agreement, window=False,
                                  ranges=ranges, grid=base_grid,
                                  pose=state.pose):
            self._quarantine_items(i, [item])
            return
        if self._finish_step(i, state, od, 1, matched, closed, base_grid,
                             base_gen, scan.header.stamp,
                             travel_cells=travel_cells,
                             enq_t=self._oldest_enq([item])):
            self._emit_fuse_spans(i, [item])

    def _reject_low_agreement(self, i: int,
                              items: Optional[List] = None) -> None:
        """Degraded-mode gate, shared by the window and single paths:
        near-zero agreement means essentially ALL of the evidence landed
        in known-free space — a garbage burst (glitching sensor, grossly
        misanchored odometry) that must not overwrite known-good map.
        Nothing installs; like a stale-step drop, the pairing chain
        resets so the next step bootstraps cleanly.

        The rejection is also a maximum-badness watchdog observation
        (recovery/): a STREAK of garbage bursts is estimator divergence,
        and the declaring burst's evidence moves to the quarantine
        buffer like any other diverged-robot evidence."""
        with self._state_lock:
            self._prev_paired[i] = None
            self._prev_matched[i] = False
        if self._recovery is not None \
                and self._recovery.watchdog.observe_rejected(i):
            self._declare_diverged(i)
            if items:
                self._quarantine_items(i, items)
        # Counters outside the lock (single tick-thread writer, like
        # every mapper counter). A rejected step is still a
        # low-agreement OBSERVATION: that telemetry counter keeps its
        # pre-rejection meaning (operators alert on it); rejection only
        # changes what happens to the evidence.
        self.n_windows_rejected_low_agreement += 1
        M.counters.inc("mapper.windows_rejected_low_agreement")
        self.n_low_agreement_windows += 1
        M.counters.inc("mapper.low_agreement_windows")

    # -- estimator guardrails (recovery/) ------------------------------------

    def _diverged(self, i: int) -> bool:
        return (self._recovery is not None
                and self._recovery.watchdog.is_diverged(i))

    def _observe_watchdog(self, i: int, matched: bool, key_added: bool,
                          agreement: float, window: bool,
                          ranges=None, grid=None, pose=None) -> bool:
        """Feed one step's health sample to the divergence watchdog;
        returns True when the observation DECLARES divergence (the
        caller then quarantines the step's evidence instead of
        installing it).

        Observation policy — FULL scan cadence: key steps carry the
        diag's pre-fusion agreement + match telemetry; window steps
        carry the window mean; sub-gate single steps sample
        models.slam.scan_agreement at the post-step pose (their diag
        agreement is a neutral 1.0 — no evidence was added — but the
        SCAN is still a health sample, and a ghosting sensor fires
        every scan, not every 0.1 m of travel)."""
        if self._recovery is None:
            return False
        if not key_added and not window and ranges is not None:
            agreement = float(self._S.scan_agreement(
                self.cfg, grid, self._jnp.asarray(ranges), pose))
        cov_trace = None
        if key_added and matched and self._last_cov[i] is not None:
            cov_trace = float(np.sum(self._last_cov[i]))
        declared = self._recovery.watchdog.observe(
            i, key_added, matched, agreement, cov_trace)
        if declared:
            self._declare_diverged(i)
        return declared

    def _declare_diverged(self, i: int) -> None:
        """ESTIMATOR_DIVERGED side effects: the fleet health ladder gets
        the rung (brain coasts the robot, auction reassigns its
        frontier), the relocalizer's streak starts clean — and the
        flight recorder dumps a postmortem (the declaration is exactly
        the moment the preceding transitions explain)."""
        if self._health is not None:
            self._health.note_estimator(i, True)
        self._recovery.relocalizer.reset(i)
        M.counters.inc("mapper.estimator_diverged_events")
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("estimator_diverged", robot=i)
        # Async: the declaration happens ON the tick thread — the
        # snapshot is taken here (deterministic content) but the
        # multi-MB json+disk write must not stall every robot's fusion
        # at exactly the moment an estimator is struggling.
        flight_recorder.dump_async(f"watchdog_divergence_robot{i}")

    def _quarantine_items(self, i: int, items: List) -> None:
        """Buffer (scan, odom) pairs instead of fusing them; bounded —
        oldest evidence drops first (its pairing is the most stale)."""
        q = self._quarantine[i]
        q.extend(items)
        cap = self.cfg.recovery.quarantine_cap
        overflow = len(q) - cap
        if overflow > 0:
            del q[:overflow]
            self.n_quarantine_overflow += overflow
        self.n_scans_quarantined += len(items)
        M.counters.inc("mapper.scans_quarantined", len(items))

    def _quarantine_and_relocalize(self, i: int, items: List,
                                   ranges) -> None:
        """One quarantine tick for robot i: buffer the evidence, then
        attempt relocalization with the freshest scan against the live
        shared map (clean by construction — this robot's garbage was
        never fused). A verified re-anchor re-admits the robot through
        the SetInitialPose path semantics (fresh chain, kept map).
        `ranges` is the freshest scan's device row from the tick's
        batched upload; `region_revision` keys the relocalizer's pyramid
        cache so a steady-state attempt reuses its pyramids."""
        self._quarantine_items(i, items)
        scan = items[-1][0]
        with self._state_lock:
            grid = self.shared_grid
            # Captured WITH the grid: the relocalizer refuses to cache a
            # pyramid whose region revision is newer than this (a
            # restore landing after the snapshot must not stamp a
            # pyramid built from the old grid as current).
            base_rev = self.map_revision
            guess = np.asarray(self.states[i].pose, np.float32)
        pose = self._recovery.relocalizer.attempt_for(
            i, self.cfg, grid, ranges, guess,
            region_rev_fn=self.region_revision,
            grid_revision=base_rev if self._serving_enabled else None)
        M.counters.inc("mapper.relocalization_attempts")
        if pose is None:
            return
        self.reset_robot_pose(i, pose)
        with self._state_lock:
            # Quarantined-era stragglers still in flight are older than
            # the verifying scan — the stale watermark rejects them.
            self._last_accepted_stamp[i] = max(
                self._last_accepted_stamp[i], scan.header.stamp)
            self._quarantine[i].clear()
        self._recovery.watchdog.readmit(i)
        if self._health is not None:
            self._health.note_estimator(i, False)
        self.n_relocalizations += 1
        M.counters.inc("mapper.relocalizations")
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("relocalized", robot=i,
                               n=self.n_relocalizations)

    @staticmethod
    def _oldest_enq(items: List):
        """Oldest pipeline enqueue stamp among a step's work items —
        the scan→served chain measures the WORST-case freshness of the
        step's evidence. None when no ledger is armed."""
        return min((it[3] for it in items
                    if len(it) > 3 and it[3] is not None),
                   default=None)

    def _travel_cells(self, motion) -> int:
        """Odometric path-length bound of a step's window, grid cells:
        the touched-tile box's interior-pose slack (`_touched_box`).
        `motion` is the step's [(wl, wr, dt), ...] equivalent-wheel
        list — |mean wheel| x coeff x dt bounds each sample's
        displacement (rotation moves no patch origin)."""
        coeff = self.cfg.robot.speed_coeff_m_per_unit_s
        travel_m = sum(abs((wl + wr) * 0.5) * coeff * dt
                       for wl, wr, dt in motion)
        return int(travel_m / self.cfg.grid.resolution_m) + 1

    def _finish_step(self, i: int, state, od: Odometry, n_scans: int,
                     matched: bool, closed: bool, base_grid,
                     base_gen: int, newest_stamp: float = -float("inf"),
                     travel_cells: int = 0,
                     enq_t: Optional[float] = None) -> bool:
        """Install the step's results; returns False when the step was
        dropped as stale (callers gate their own telemetry on it).
        `newest_stamp` is the newest fused scan's stamp — it advances
        the robot's stale-rejection watermark only when the step really
        installs."""
        # Fused path: the dirty-tile hint comes from the device (exact
        # patch extents, fuse_kernel.touched_tile_box) instead of the
        # host's half-extent approximation. Computed AND fetched before
        # the lock — a stale-dropped step just wastes one tiny call.
        touched_box = self._touched_box(i, state, travel_cells)
        rev_installed = None
        with self._state_lock:
            if self.shared_grid is not base_grid \
                    or self._state_gen[i] != base_gen:
                # Another thread replaced fleet or robot state while this
                # step was in flight — grid identity catches /load
                # swapping the shared grid; the generation counter is
                # defense-in-depth for any mutator the identity check
                # can't see (bus-delivered /initialpose is serialized
                # against tick by the node's _cb_lock, but restore_states
                # runs on the HTTP thread, and correctness here shouldn't
                # hinge on grid-object-identity subtleties). Installing
                # ANY of the step's results (grid, state, or a ring
                # rebuild over the stale ring) would silently revert that
                # mutation to win one scan's evidence. Drop the step —
                # including _odom_motion's pairing side effect, so the
                # next pair bootstraps in the live odom frame instead of
                # integrating the stale-to-live frame jump — and keep the
                # fused/matched/closed counters honest.
                self._prev_paired[i] = None
                self._prev_matched[i] = False
                M.counters.inc("mapper.steps_dropped_stale")
                return False
            # The step's output grid is the fleet's new shared map;
            # every state keeps aliasing it (arrays are immutable, so
            # aliasing is free).
            self.shared_grid = state.grid
            self.states[i] = state
            self._last_accepted_stamp[i] = max(
                self._last_accepted_stamp[i], newest_stamp)
            if closed and self.n_robots > 1:
                # The closure's in-step repair re-fused only robot
                # i's ring; rebuild the shared map from EVERY robot's
                # ring so fleet-mates' walls survive
                # (models/fleet._close_loops, host-orchestrated).
                self.shared_grid = self._refuse_all_rings()
            if closed and self._map_prior is not None:
                # Ring re-fusions rebuild from an empty grid, so every
                # cell without live key-scan evidence (log-odds exactly
                # 0) reverts to unknown — which would silently erase an
                # imported map prior at the first closure. Backfill:
                # live evidence wins wherever any exists; the prior
                # keeps the unobserved remainder of the known map.
                jnp_ = self._jnp
                self.shared_grid = jnp_.where(self.shared_grid == 0.0,
                                              self._map_prior,
                                              self.shared_grid)
            for j in range(self.n_robots):
                self.states[j] = self.states[j]._replace(
                    grid=self.shared_grid)
            # Odometry-scale calibration sample (report.pdf §III.D/§V.B:
            # SPEED_COEFF was hand-measured with 13% CV; wheel slip and
            # battery level drift it in the field). Between consecutive
            # installed steps, the SLAM displacement over the odometry
            # displacement estimates true_coeff/configured_coeff — on
            # matched, closure-free, mostly-straight, non-trivial motion
            # only (closures teleport the estimate; pivots measure the
            # wheel BASE, not the coeff).
            prev = self._correction[i]
            new_est = np.asarray(state.pose, np.float32)
            new_odo = np.asarray([od.pose.x, od.pose.y, od.pose.theta],
                                 np.float32)
            if self._serving_enabled:
                # Serving delta tracking: this install changed the map.
                # A closure re-fused (possibly) everything; a plain
                # step touched at most its fusion patch's tiles —
                # device-computed under the fused path, host-estimated
                # under the classic one.
                self.map_revision += 1
                rev_installed = self.map_revision
                if closed:
                    self._mark_dirty_all()
                elif touched_box is not None:
                    self._mark_dirty_box(touched_box)
                else:
                    self._mark_dirty_patch(new_est[:2])
            if prev is not None and matched and self._prev_matched[i] \
                    and not closed:
                # matched-after-matched only: the re-convergence snap
                # after a dead-reckoned stretch puts several steps of
                # accumulated correction into ONE step's d_slam and
                # would bias the scale (review r5).
                d_slam = float(np.hypot(*(new_est[:2] - prev[0][:2])))
                d_odo = float(np.hypot(*(new_odo[:2] - prev[1][:2])))
                dth = abs(float((new_odo[2] - prev[1][2] + np.pi)
                                % (2 * np.pi) - np.pi))
                if d_odo > 0.01 and dth < 0.2 \
                        and 0.5 < d_slam / d_odo < 2.0:
                    k = self._calib_decay
                    self._calib_odo[i] = self._calib_odo[i] * k + d_odo
                    self._calib_slam[i] = self._calib_slam[i] * k + d_slam
                    self._calib_n[i] += 1
            self._prev_matched[i] = matched
            # The installed (estimate, paired odom) pair IS the live
            # map->odom correction for robot i (depth_anchor consumers).
            self._correction[i] = (new_est, new_odo)
        if self._pipeline is not None and rev_installed is not None:
            # Install waypoint, OUTSIDE the state lock (the ledger has
            # its own leaf lock): the revision captured at the bump,
            # the step's oldest enqueue stamp, the deterministic tick.
            self._pipeline.installed(rev_installed, enq_t=enq_t,
                                     tick=self._tick_no)
        self.n_scans_fused += n_scans
        M.counters.inc("mapper.scans_fused", n_scans)
        if matched:
            M.counters.inc("mapper.scan_matches")
        if closed:
            self.n_loops_closed += 1
            M.counters.inc("mapper.loops_closed")
        return True

    def publish_graph(self) -> None:
        """The fleet's pose graphs as `/graph` (GraphMarkers) — the
        slam_toolbox interactive-mode graph view (slam_config.yaml:32),
        served continuously instead of behind a service call. Loop
        edges = non-consecutive constraints."""
        from jax_mapping.bridge.messages import GraphMarkers
        with self._state_lock:               # refs only; fetch after
            states = list(self.states)
        nodes, nrob, edges, isloop = [], [], [], []
        cap = self.cfg.loop.max_poses
        for i, st in enumerate(states):
            g = st.graph
            poses = np.asarray(g.poses[:cap], np.float32)
            valid = np.asarray(g.pose_valid[:cap])
            for k in np.nonzero(valid)[0]:
                nodes.append(poses[k, :2])
                nrob.append(i)
            eij = np.asarray(g.edge_ij)
            evalid = np.asarray(g.edge_valid)
            for k in np.nonzero(evalid)[0]:
                a, b = int(eij[k, 0]), int(eij[k, 1])
                if not (valid[a] and valid[b]):
                    continue
                edges.append([poses[a, :2], poses[b, :2]])
                isloop.append(abs(b - a) > 1)
        self.graph_pub.publish(GraphMarkers(
            header=Header.now("map"),
            nodes_xy=np.asarray(nodes, np.float32).reshape(-1, 2),
            node_robot=np.asarray(nrob, np.int32),
            edges_xy=np.asarray(edges, np.float32).reshape(-1, 2, 2),
            edge_is_loop=np.asarray(isloop, bool)))

    def calibration(self) -> Optional[dict]:
        """Fleet odometry-scale estimate from the accumulated matched
        straight-motion samples, or None before any accumulate.

        `odom_scale` ~ true/configured displacement per wheel unit: the
        live re-measurement of the reference's hand-calibrated
        SPEED_COEFF (report.pdf §III.D measured 13% CV between runs),
        EWMA-weighted so it tracks battery/slip drift.
        `suggested_speed_coeff` = configured * scale is what an operator
        would write back into RobotConfig after a drive. `per_robot`
        exposes each robot's own scale (None before its first sample) so
        one slipping wheel is visible instead of silently contaminating
        the fleet figure.

        LOCK-FREE reads, like the /status counter reads: stale-by-one
        telemetry beats the health endpoint blocking behind a lock-held
        fleet ring re-fusion."""
        odo = sum(self._calib_odo)
        slam = sum(self._calib_slam)
        n = sum(self._calib_n)
        if n == 0 or odo <= 0.0:
            return None
        per_robot = [
            (round(s / o, 4) if o > 0.0 else None)
            for s, o in zip(self._calib_slam, self._calib_odo)]
        scale = slam / odo
        return {
            "odom_scale": round(scale, 4),
            "suggested_speed_coeff": round(
                self.cfg.robot.speed_coeff_m_per_unit_s * scale, 7),
            "n_samples": n,
            "per_robot": per_robot,
        }

    def _refuse_all_rings(self):
        """Shared-map repair across the fleet: re-fuse every robot's
        key-scan ring at its (optimised) graph poses, masked on pose
        validity. Caller holds the state lock."""
        G_, jnp = self._G, self._jnp
        cap = self.cfg.loop.max_poses
        grid = G_.empty_grid(self.cfg.grid)
        rings = jnp.concatenate(
            [st.scan_ring for st in self.states], axis=0)
        poses = jnp.concatenate(
            [st.graph.poses[:cap] for st in self.states], axis=0)
        valid = jnp.concatenate(
            [st.graph.pose_valid[:cap] for st in self.states], axis=0)
        # Bucketed entry: R x cap is config-fixed but not a bucket edge
        # for every fleet size — bucketing keeps one compiled variant
        # per bucket, never one per fleet-size drift. The midpoint
        # bucket set means the common configs pay nothing (2x64=128 and
        # 3x64=192 are both exact edges) and padding never exceeds a
        # third of the rows on this rare (closure-repair) path.
        return G_.fuse_scans_bucketed(self.cfg.grid, self.cfg.scan, grid,
                                      rings, poses, valid)

    def _publish_correction(self, i: int, scan: LaserScan,
                            od: Odometry) -> None:
        """map->odom correction TF: est ⊖ odom (slam_toolbox's role)."""
        est = np.asarray(self.states[i].pose)
        if self.world is not None:
            # TF consumers live in the fixed world frame; the estimator
            # runs robocentric — translate at the publish boundary.
            est = est + np.array([*self.world.offset_xy(), 0.0],
                                 np.float32)
        o = od.pose
        ns = robot_ns(i, self.n_robots)
        c, s = np.cos(est[2] - o.theta), np.sin(est[2] - o.theta)
        self.tf.set_transform(TransformStamped(
            header=Header(stamp=scan.header.stamp, frame_id="map"),
            child_frame_id=f"{ns}odom",
            x=float(est[0] - (c * o.x - s * o.y)),
            y=float(est[1] - (s * o.x + c * o.y)),
            theta=float(est[2] - o.theta)))

    # -- exports ------------------------------------------------------------

    # -- 3D-coupling surface (bridge/voxel_mapper.py) ------------------------

    def depth_anchor(self, i: int):
        """Consistent host-side snapshot the 3D mapper uses to fuse depth
        at CORRECTED poses and to anchor depth keyframes to this robot's
        graph: (gen, est_pose, odom_pose, node_idx, node_pose,
        n_keyscans), or None before the first installed step / while the
        chain is empty. All values fetched under the state lock so the
        correction basis and the graph tip belong to the same step."""
        # Snapshot refs under the lock, fetch device data AFTER releasing
        # it: states are immutable pytrees, so the snapshot stays
        # consistent, and a blocking device->host transfer inside the
        # lock would stall the 2D hot path's _finish_step.
        with self._state_lock:
            corr = self._correction[i]
            if corr is None:
                return None
            st = self.states[i]
            gen = self._state_gen[i]
        if self.world is not None:
            # The voxel mapper fuses in world frame; est and graph-node
            # poses are robocentric (odom is odom-frame: untouched).
            shift3 = np.array([*self.world.offset_xy(), 0.0], np.float32)
            corr = (np.asarray(corr[0], np.float32) + shift3, corr[1])
        n = int(st.graph.n_poses)
        if n == 0:
            # A correction without a graph: localization mode tracks the
            # pose against a frozen map and never grows the graph. The
            # 3D mapper must still fuse at CORRECTED poses (or the voxel
            # map shears off the frozen 2D map under odometry drift);
            # node_idx = -1 says "no node to anchor keyframes to" — and
            # with no closures possible, nothing would re-fuse them.
            return (gen, corr[0], corr[1], -1, corr[0],
                    int(st.n_keyscans))
        node_pose = np.asarray(st.graph.poses[n - 1], np.float32)
        if self.world is not None:
            node_pose = node_pose + shift3
        return (gen, corr[0], corr[1], n - 1, node_pose,
                int(st.n_keyscans))

    def graph_snapshot(self, i: int):
        """(gen, poses (cap, 3) np, pose_valid (cap,) np, n_poses,
        n_keyscans) for keyframe re-anchoring after a loop closure."""
        with self._state_lock:      # refs only; transfers after release
            st = self.states[i]
            gen = self._state_gen[i]
        cap = self.cfg.loop.max_poses
        poses = np.asarray(st.graph.poses[:cap], np.float32)
        if self.world is not None:
            # World frame, same as depth_anchor: graph poses translate
            # with every window shift, the offset undoes it — node
            # poses stay shift-invariant for keyframe re-anchoring.
            poses = poses + np.array([*self.world.offset_xy(), 0.0],
                                     np.float32)
        return (gen, poses, np.asarray(st.graph.pose_valid[:cap]),
                int(st.graph.n_poses), int(st.n_keyscans))

    def merged_grid(self):
        """The fleet's shared global map (kept under the historical name:
        round 3's design held one full grid PER robot and max-merged on
        every publish — 64 MB x R at production size; the shared-grid
        redesign makes this a constant-time read)."""
        with self._state_lock:
            return self.shared_grid

    def publish_map(self) -> None:
        g = self.cfg.grid
        lo = np.asarray(self.merged_grid())
        origin = g.origin_m
        if self.world is not None:
            # The published grid is the WINDOW; its origin rides the
            # window so /map consumers see it at the right world pose.
            off = self.world.offset_xy()
            origin = (float(origin[0] + off[0]), float(origin[1] + off[1]))
        msg = occupancy_from_logodds(lo, g.occ_threshold, g.free_threshold,
                                     g.resolution_m, origin)
        self._last_map_stamp = msg.header.stamp
        self.map_pub.publish(msg)
        self.map_updates_pub.publish(msg)

    def _reassign_dead(self, assignment: np.ndarray, targets: np.ndarray,
                       poses: np.ndarray) -> np.ndarray:
        """Strip unavailable robots from the frontier auction's output
        and hand their orphaned targets to the nearest available robot.

        Unavailable = DEAD (cannot map) or ESTIMATOR_DIVERGED (coasting
        while the mapper relocalizes it — a frontier pinned to it would
        stall until the re-anchor): FleetHealth.assignable_mask. The
        device-side auction cannot see health (poses is a static
        (R, ...) batch), so the fleet-reassignment contract lives here
        on the host: the robot's assignment becomes -1 (the brain and
        planner stop steering/planning for it), and any frontier ONLY it
        was assigned to transfers to the closest available robot — mid-
        mission robot loss shrinks the fleet, not the explored map."""
        if self._health is None or len(assignment) == 0:
            return assignment
        alive = self._health.assignable_mask()[:len(assignment)]
        if alive.all() or not alive.any():
            return assignment
        assignment = assignment.copy()
        live_idx = np.nonzero(alive)[0]
        for d in np.nonzero(~alive)[0]:
            a = int(assignment[d])
            assignment[d] = -1
            if 0 <= a < len(targets) \
                    and not np.any(assignment[live_idx] == a):
                # Orphaned frontier: nearest alive robot adopts it.
                dists = np.hypot(poses[live_idx, 0] - targets[a, 0],
                                 poses[live_idx, 1] - targets[a, 1])
                assignment[live_idx[int(np.argmin(dists))]] = a
                M.counters.inc("mapper.frontiers_reassigned")
        return assignment

    def _apply_blacklist(self, assignment: np.ndarray,
                         targets: np.ndarray,
                         poses: np.ndarray) -> np.ndarray:
        """Anti-stuck rung 3 (recovery/antistuck.FrontierBlacklist):
        a robot repeatedly stuck en route to a frontier has proven it
        unreachable-in-practice — strip the assignment and hand the
        robot the nearest frontier NOT blacklisted for it (goal
        reassignment), or -1 (blind cruise under the shield) when none
        remains. Per-robot: the frontier stays auctionable to robots
        approaching from elsewhere. Tolerance = one clustering cell,
        the same echo tolerance the brain's waypoint match uses."""
        if self._recovery is None or len(assignment) == 0 \
                or len(targets) == 0:
            return assignment
        bl = self._recovery.blacklist
        if not bl.entries():
            return assignment
        tol = (self.cfg.grid.resolution_m * self.cfg.frontier.downsample
               * self.cfg.frontier.cluster_downsample)
        assignment = assignment.copy()
        for i in range(len(assignment)):
            a = int(assignment[i])
            if not 0 <= a < len(targets) \
                    or not bl.is_blacklisted(i, targets[a], tol):
                continue
            allowed = [j for j in range(len(targets))
                       if not bl.is_blacklisted(i, targets[j], tol)]
            if allowed:
                p = poses[i] if i < len(poses) else targets[a]
                dists = [float(np.hypot(targets[j][0] - p[0],
                                        targets[j][1] - p[1]))
                         for j in allowed]
                assignment[i] = allowed[int(np.argmin(dists))]
            else:
                assignment[i] = -1
            M.counters.inc("mapper.frontiers_blacklist_redirects")
        return assignment

    def _frontier_basis(self, lo, rev: int):
        """The grid frontier assignment runs on + its non-tile-tracked
        cache key. The PLANNING grid when a provider is wired (launch:
        the planner's voxel-overlaid basis) — the auction and the
        waypoint descent must see the same map, or a frontier whose only
        corridor is blocked by depth-only obstacles gets assigned
        forever while every plan to it fails."""
        if self.frontier_grid_provider is None:
            return lo, None
        try:
            # Key BEFORE basis (the serving-snapshot ordering): an
            # overlay advancing in between leaves new content under an
            # older key — the next key read invalidates and heals — while
            # the reverse order could stamp old content current forever.
            key = None
            if self.frontier_grid_key_provider is not None:
                key = ("overlay", self.frontier_grid_key_provider())
            try:
                # rev is only a valid content key while revision
                # tracking is live: with serving disabled map_revision
                # is frozen at 0, and keying the planner's overlay
                # cache on a constant would serve the FIRST publish's
                # basis forever. None = identity-keyed fallback.
                lo_rev = rev if self._serving_enabled else None
                basis = self.frontier_grid_provider(lo, lo_rev)
            except TypeError:
                # Legacy no-arg provider (pre-snapshot contract): it
                # reads its own basis.
                basis = self.frontier_grid_provider()
            if key is not None:
                return basis, key
            # Unkeyed provider output: a fresh sentinel per publish makes
            # the incremental pipeline treat every tile as dirty — a full
            # recompute, never a stale overlay served as current.
            return basis, ("unkeyed", object())
        except Exception:                # noqa: BLE001
            # Provider trouble must not take down frontier publishing;
            # the bare 2D map is the round-4 behavior.
            import traceback
            traceback.print_exc()
            return lo, None

    def _frontier_incremental(self):
        """The incremental pipeline, or None (disabled config, no
        revision tracking, or a latched geometry rejection). Decay-
        aware scoring rides the incremental path too: the pipeline
        carries the HEALED/STALE mask tile-incrementally alongside the
        other coarse masks (a decay pass bumps every tile revision, so
        staleness refreshes with them — ROADMAP item 7c)."""
        if not self.cfg.frontier.incremental or self._tile_rev is None \
                or self._frontier_pipeline_failed:
            return None
        if self._frontier_pipeline is None:
            from jax_mapping.ops.frontier_incremental import \
                IncrementalFrontierPipeline
            try:
                self._frontier_pipeline = IncrementalFrontierPipeline(
                    self.cfg.frontier, self.cfg.grid,
                    self.cfg.serving.tile_cells)
            except ValueError as e:
                print(f"[mapper] incremental frontier pipeline disabled "
                      f"({e}); publishing via full recompute", flush=True)
                self._frontier_pipeline_failed = True
                return None
        return self._frontier_pipeline

    def frontier_stats(self) -> Optional[dict]:
        """Incremental-pipeline observability for /status + /metrics
        (lock-free reads, the /status counter convention); None until
        the pipeline exists."""
        p = self._frontier_pipeline
        return None if p is None else p.status()

    def publish_frontiers(self) -> None:
        # Whole-publish latency stage (obs histogram family): covers
        # BOTH the incremental pipeline and the full-recompute fallback
        # plus the reassign/blacklist post-passes — the number an
        # operator compares against the control period. The inner
        # `mapper.frontier_publish` stage keeps timing just the
        # incremental compute (PR 6's meaning, unchanged).
        with M.stages.stage("mapper.publish_frontiers"):
            self._publish_frontiers_body()

    def _publish_frontiers_body(self) -> None:
        with self._state_lock:
            # ONE consistent section for everything this publish uses:
            # poses, grid, revision and the dirty-tile snapshot. (The
            # historical code read poses under the lock but snapshotted
            # merged_grid() after releasing it, so a concurrent install
            # — restore, prior seed, another robot's step — could pair a
            # new map with old poses.) The reassign/blacklist post-
            # passes below reuse this same snapshot.
            poses = np.stack([np.asarray(st.pose) for st in self.states])
            lo = self.shared_grid
            rev = self.map_revision
            tile_rev = None
            if self._tile_rev is not None:
                with self._dirty_lock:
                    tile_rev = self._tile_rev.copy()
        if tile_rev is not None and self.world is not None:
            # The pipeline runs at window scale: slice its view of the
            # logical revision lattice to the resident window.
            r0, c0 = self.world.origin_tile
            wt = self.world.window_tiles
            tile_rev = np.ascontiguousarray(
                tile_rev[r0:r0 + wt, c0:c0 + wt])
        lo, extra_key = self._frontier_basis(lo, rev)
        if self.world is not None:
            # A shift changes what window-local coordinates MEAN — the
            # origin in the key invalidates every cached tile across
            # one (the frontier pipeline's extra_key contract).
            extra_key = ("worigin", self.world.origin_tile, extra_key)
        pipeline = self._frontier_incremental()
        if pipeline is not None:
            with M.stages.stage("mapper.frontier_publish"):
                pub = pipeline.compute(lo, poses, tile_rev, rev,
                                       extra_key=extra_key)
            targets = pub.targets
            sizes = pub.sizes
            assignment = pub.assignment
            stamp_rev = pub.revision
            M.counters.inc("mapper.frontier_recomputes"
                           if pub.recomputed else "mapper.frontier_skips")
        else:
            fr = self._F.compute_frontiers(self.cfg.frontier,
                                           self.cfg.grid, lo,
                                           self._jnp.asarray(poses))
            targets = np.asarray(fr.targets)
            sizes = np.asarray(fr.sizes)
            assignment = np.asarray(fr.assignment)
            stamp_rev = rev if self._serving_enabled else -1
            M.counters.inc("mapper.frontier_recomputes")
        if self.world is not None and len(targets):
            # Publish boundary: the pipeline computed in WINDOW frame;
            # targets cross into world frame here, before the post-
            # passes (blacklist entries are world-frame) and the wire.
            # Copy first — pub.targets may alias the pipeline's cache.
            off = self.world.offset_xy()
            targets = np.asarray(targets, np.float32) + off[None, :]
        if self.world is not None:
            poses = poses.copy()
            poses[:, :2] += self.world.offset_xy()[None, :]
        # Post-passes run FRESH even on a skipped recompute (health and
        # blacklists move on their own clocks); they copy-on-write, so
        # the pipeline's cached assignment is never mutated.
        assignment = self._reassign_dead(assignment, targets, poses)
        assignment = self._apply_blacklist(assignment, targets, poses)
        hdr = Header.now("map")    # one stamp for the whole publish cycle
        self.frontiers_pub.publish(FrontierArray(
            header=hdr,
            targets_xy=targets,
            sizes=sizes,
            assignment=assignment,
            map_revision=int(stamp_rev)))
        self.pose_pub.publish([
            {"x": float(p[0]), "y": float(p[1]), "theta": float(p[2]),
             "stamp": hdr.stamp,
             "cov": (None if self._last_cov[i] is None
                     else self._last_cov[i].tolist())}
            for i, p in enumerate(poses)])
