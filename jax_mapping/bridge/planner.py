"""Planner node: RViz SetGoal -> `/plan` path + steering waypoint.

Closes the navigation loop the reference left open: RViz's SetGoal tool
published `/goal_pose` with no consumer (Nav2 was future work, report.pdf
§VI.2; `server/rviz_config.rviz:193-198`). The brain's round-4 goal seek
steers STRAIGHT at the goal under the reactive shield, so a goal behind a
wall was only "not crashed into", never reached. This node is the
Nav2-shaped global planner over the framework's own map:

* On a timer (PlannerConfig.period_s) while a navigation goal is set:
  snapshot the mapper's shared grid + the robot's SLAM-corrected pose,
  run `ops.planner.plan_to_goal` (goal-seeded obstacle-aware cost-to-go
  + greedy descent, one jit), and publish
    - `/plan`          Path: the world-frame waypoint list (RViz Path
                       display; nav_msgs/Path at the rclpy boundary),
    - `/goal_waypoint` Pose2D + reachable flag: the lookahead steering
                       target the brain prefers over the raw goal while
                       fresh (PlannerConfig.waypoint_ttl_s).
* Unreachable goals publish an EMPTY plan with reachable=False — the
  brain keeps round-4 straight-line-seek-under-shield behavior, and the
  operator sees the empty path in RViz.

Frames: planning runs in the map frame (the grid's frame). The brain
steers from its odometry pose toward a map-frame waypoint — the same
map~odom approximation its round-4 straight-line seek already makes; the
SLAM correction enters through the planned PATH being anchored to the
corrected map.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.messages import Header, Path, Waypoint
from jax_mapping.bridge.node import Node
from jax_mapping.config import SlamConfig
from jax_mapping.utils.profiling import global_metrics as M


class PlannerNode(Node):
    """Global planner for the fleet's goal robot (robot 0, the one RViz's
    SetGoal drives — brain._goal_cb's convention)."""

    def __init__(self, cfg: SlamConfig, bus: Bus, mapper, brain=None,
                 robot_idx: int = 0, voxel_mapper=None, health=None):
        super().__init__("planner", bus)
        self.cfg = cfg
        self.mapper = mapper
        self.brain = brain
        self.robot_idx = robot_idx
        #: Shared degraded-mode registry (resilience/health.py): plans
        #: are never computed for DEAD robots — a BFS per period toward
        #: a robot that cannot move is pure waste, and its manual goal
        #: (if any) must wait for the rejoin. None = plan for everyone.
        self._health = health
        self.n_plans_skipped_dead = 0
        # 3D-aware planning (PlannerConfig.use_voxel_obstacles): with a
        # voxel mapper attached, plans search the 2D grid overlaid with
        # the 3D map's obstacle slice — depth-camera obstacles the LiDAR
        # plane misses block paths. The overlay needs equal cell sizes;
        # validated HERE (once, loudly) rather than per tick, where the
        # node's guarded callbacks would swallow the error and silently
        # kill every plan. A mismatched config degrades to 2D-only.
        self.voxel_mapper = voxel_mapper
        if (voxel_mapper is not None and cfg.planner.use_voxel_obstacles
                and abs(cfg.voxel.resolution_m - cfg.grid.resolution_m)
                > 1e-9):
            print("[planner] voxel resolution "
                  f"{cfg.voxel.resolution_m} != grid "
                  f"{cfg.grid.resolution_m}; 3D obstacle overlay "
                  "DISABLED — plans search the 2D map only", flush=True)
            self.voxel_mapper = None
        self.plan_pub = self.create_publisher("/plan")
        self.wp_pub = self.create_publisher("/goal_waypoint")
        # Standalone (no brain reference): track the goal from the topic.
        # With a brain, the brain owns the goal (set by /goal_pose, cleared
        # on arrival) and this node reads it, so a reached goal stops
        # replanning without a second arrival bookkeeper.
        self._goal: Optional[tuple] = None
        if brain is None:
            self.create_subscription("/goal_pose", self._goal_cb)
        # Frontier waypoints (PlannerConfig.frontier_waypoints): per-robot
        # planned steering targets toward /frontiers assignments, so fleet
        # exploration navigates around walls instead of straight-line
        # seeking into them. The brain matches each waypoint to its
        # robot's CURRENT assignment via the goal echo.
        self._frontiers = None
        self._lo_cache = None
        #: Overlay work accounting (satellite of the incremental
        #: frontier pipeline): reuses = keyed or identity cache hits,
        #: rebuilds = full obstacle_slice reductions actually paid.
        self.n_overlay_rebuilds = 0
        self.n_overlay_reuses = 0
        self.create_subscription("/frontiers", self._frontiers_cb)
        self.fwp_pub = self.create_publisher("/frontier_waypoints")
        self.n_plans = 0
        self.n_frontier_plans = 0
        self.n_goal_fields = 0
        self.last_reachable: Optional[bool] = None
        #: Per-robot manual-plan reachability (fleet goals).
        self.reachable_by_robot: dict = {}
        #: Planner tick counter — the staleness clock for /frontiers.
        #: The repo's TTL doctrine (brain._steer_target): freshness in
        #: the DETERMINISTIC time base, never wall time, or slow hosts
        #: silently change trajectories.
        self._n_ticks = 0
        self.create_timer(cfg.planner.period_s, self.tick)

    def _goal_cb(self, msg) -> None:
        # Same ingress guard as ThymioBrain._goal_cb and the HTTP
        # route (GridConfig.contains_m): in standalone/live mode this
        # subscription is the ONLY goal ingress, and a NaN or
        # out-of-map goal would clip to a border cell and publish a
        # plan toward a place that does not exist, replanning forever.
        x, y = float(msg.x), float(msg.y)
        if not self.cfg.grid.contains_m(x, y):
            print(f"[planner] ignoring non-finite or out-of-map goal "
                  f"({x}, {y})", flush=True)
            return
        self._goal = (x, y)

    def _frontiers_cb(self, msg) -> None:
        # Reorder watermark (the brain's _fresher rule): a stale
        # /frontiers message arriving after a fresher one must not
        # resurrect assignments the mapper has since dropped — the
        # planner would burn a BFS per period toward each of them.
        if self._frontiers is not None and \
                msg.header.stamp < self._frontiers[0].header.stamp:
            return
        self._frontiers = (msg, self._n_ticks)

    def _current_goal(self) -> Optional[tuple]:
        if self.brain is not None:
            return self.brain.nav_goal()
        return self._goal

    def _manual_goals(self) -> list:
        """Per-robot manual goals (None where unset). Standalone mode
        has only the /goal_pose-tracked goal for robot_idx."""
        if self.brain is not None:
            return self.brain.nav_goals()
        goals = [None] * self.mapper.n_robots
        if self._goal is not None:
            goals[self.robot_idx] = self._goal
        return goals

    def _robot_pose_xy(self, i: Optional[int] = None
                       ) -> Optional[np.ndarray]:
        """SLAM-corrected pose when the mapper has stepped; the brain's
        odometry pose before that (map == odom until the first
        correction)."""
        if i is None:
            i = self.robot_idx
        anchor = self.mapper.depth_anchor(i)
        if anchor is not None:
            return np.asarray(anchor[1], np.float32)[:2]
        if self.brain is not None:
            return self.brain.robot_pose(i)[:2]
        return None

    def overlay_key(self):
        """The voxel overlay's content key (its serving revision), or
        None when no overlay applies — the NON-tile-tracked half of the
        planning basis. The mapper's incremental frontier pipeline
        invalidates its coarse-mask cache when this moves (2D-map
        changes it can see per tile; overlay changes only through
        this)."""
        if self.voxel_mapper is None \
                or not self.cfg.planner.use_voxel_obstacles:
            return None
        return self.voxel_mapper.serving_revision()

    def _planning_grid(self, lo=None, lo_rev=None):
        """The log-odds grid plans search: the shared 2D map, overlaid
        with the 3D obstacle slice when a voxel mapper is attached.

        Keyed on (map_revision, voxel fusion key) when revision tracking
        is live — the manual-goal plan, every frontier field in a tick,
        AND the mapper's frontier publish (frontier_grid_provider) share
        one cached overlay per revision pair instead of each paying the
        full obstacle_slice reduction; array identity remains the
        fallback key when the mapper doesn't track revisions (serving
        disabled). `lo`/`lo_rev` let the mapper pass its own consistent
        snapshot so the publish's pose/grid pairing stays tear-free.

        Thread-safety: runs from two executor threads (the planner's
        tick AND the mapper's publish via frontier_grid_provider — node
        callbacks serialize per NODE), so the cache tuple is SNAPSHOTTED
        once; tuple assignment is atomic, and the worst interleaving is
        one redundant overlay computation. The cache HOLDS the keyed
        arrays (not bare id()s, whose values can be reused after
        garbage collection), so `is` is sound."""
        if lo is None:
            # Revision BEFORE the grid here too (same hazard as v_rev
            # below): an install landing between the reads must leave
            # new content under an old key (healed by the next miss),
            # never old content under the new key (served forever).
            lo_rev = (self.mapper.serving_revision()
                      if getattr(self.mapper, "_serving_enabled", False)
                      else None)
            lo = self.mapper.merged_grid()
        overlay = (self.voxel_mapper is not None
                   and self.cfg.planner.use_voxel_obstacles)
        # Revision BEFORE the grid snapshot (the PR 4 voxel-snapshot
        # ordering): a fusion landing between the two leaves newer
        # content under an older key — healed by the next call's miss —
        # while the reverse order would stamp OLD content with the new
        # key and serve it as current forever.
        v_rev = self.voxel_mapper.serving_revision() if overlay else None
        vg = self.voxel_mapper.voxel_grid() if overlay else None
        key = (lo_rev, v_rev) if lo_rev is not None else None
        cache = self._lo_cache
        if cache is not None and \
                ((key is not None and cache[0] == key)
                 or (cache[1] is lo and cache[2] is vg)):
            self.n_overlay_reuses += 1
            return cache[3]
        out = lo
        if overlay:
            from jax_mapping.ops import planner as P
            out = P.overlay_voxel_obstacles(
                self.cfg.planner, self.cfg.grid, self.cfg.voxel, lo, vg)
            self.n_overlay_rebuilds += 1
        self._lo_cache = (key, lo, vg, out)
        return out

    def _plan(self, goal, pose_xy):
        """One jitted plan; returns (path, reachable, waypoint, arrived)."""
        import jax.numpy as jnp
        from jax_mapping.ops import planner as P
        r = P.plan_to_goal(self.cfg.planner, self.cfg.frontier,
                           self.cfg.grid, self._planning_grid(),
                           jnp.asarray(np.asarray(goal, np.float32)),
                           jnp.asarray(pose_xy))
        return (np.asarray(r.path_xy)[np.asarray(r.path_valid)],
                bool(r.reachable), np.asarray(r.waypoint_xy, np.float32),
                bool(r.arrived))

    def tick(self) -> None:
        self._n_ticks += 1
        with M.stages.stage("planner.tick"):
            manual_robots = self._tick_manual_goals()
            if self.cfg.planner.frontier_waypoints:
                self._tick_frontier_waypoints(manual_robots=manual_robots)

    def _tick_manual_goals(self) -> set:
        """Plan for every robot's manual nav goal (/goal_pose is robot
        0's; fleets address the rest via {ns}goal_pose). Returns the set
        of robot indices with an active manual goal — the frontier pass
        must leave those robots alone."""
        goals = self._manual_goals()
        active: set = set()
        hdr = Header.now("map")
        alive = (self._health.alive_mask()
                 if self._health is not None else None)
        for i, goal in enumerate(goals):
            if goal is None:
                continue
            if alive is not None and i < len(alive) and not alive[i]:
                self.n_plans_skipped_dead += 1
                continue
            active.add(i)
            pose_xy = self._robot_pose_xy(i)
            if pose_xy is None:
                continue
            path, reachable, wp, arrived = self._plan(goal, pose_xy)
            if self.brain is None and arrived:
                # Standalone arrival bookkeeping: with a brain the brain
                # clears the goal (and this node reads its copy);
                # without one the planner must stop itself or it replans
                # forever.
                self._goal = None
                active.discard(i)
                continue
            self.wp_pub.publish(Waypoint(
                header=hdr, x=float(wp[0]), y=float(wp[1]),
                reachable=reachable, goal_x=float(goal[0]),
                goal_y=float(goal[1]), robot=i))
            # Per-robot reachability: the health endpoint must not keep
            # reporting robot 0's old plan as THE answer while another
            # fleet robot's goal is unreachable.
            self.reachable_by_robot[i] = reachable
            if i == self.robot_idx:
                # /plan is single-Path (the RViz display); it follows
                # the goal robot (robot 0, the SetGoal convention).
                self.plan_pub.publish(Path(header=hdr, poses_xy=path))
                self.last_reachable = reachable
            self.n_plans += 1
            M.counters.inc("planner.plans")
        # Entries for robots whose goals cleared are pruned — stale
        # reachability was the exact misleading telemetry this dict
        # exists to fix.
        self.reachable_by_robot = {
            i: v for i, v in self.reachable_by_robot.items() if i in active}
        return active

    def _tick_frontier_waypoints(self, manual_robots: set) -> None:
        """Plan per exploring robot toward its /frontiers assignment and
        publish per-robot waypoints (+ robot 0's plan for RViz when no
        manual goal claims /plan)."""
        entry = self._frontiers
        if entry is None:
            return
        fr, at_tick = entry
        if self.brain is not None and not self.brain.is_exploring:
            return                           # /stop: nothing to steer
        # A dead mapper must not keep the planner burning a BFS per
        # target per period toward frozen assignments (the brain's
        # seek_ttl_s gate would discard the waypoints anyway). Staleness
        # in PLANNER TICKS — the deterministic time base — per the TTL
        # doctrine above.
        ttl_ticks = max(1, round(self.cfg.frontier.seek_ttl_s
                                 / self.cfg.planner.period_s))
        if self._n_ticks - at_tick > ttl_ticks:
            return
        targets = np.asarray(fr.targets_xy, np.float32)
        assign = np.asarray(fr.assignment)
        hdr = Header.now("map")
        # The goal-seeded field is the dominant cost and depends only on
        # the target; the frontier auction SHARES clusters when robots
        # outnumber frontiers (assign_frontiers), so compute one field
        # per unique assigned target and descend it per robot.
        import jax.numpy as jnp
        from jax_mapping.ops import planner as P
        fields: dict = {}
        plan_lo = None                       # fetched once, on first use
        # assignable = not DEAD and not ESTIMATOR_DIVERGED: a diverged
        # robot coasts while the mapper relocalizes it — the auction's
        # post-pass has already handed its frontier elsewhere, so a
        # waypoint BFS for it is pure waste.
        avail = (self._health.assignable_mask()
                 if self._health is not None else None)
        for i in range(min(self.mapper.n_robots, len(assign))):
            if i in manual_robots:
                continue                     # a manual goal owns robot i
            if avail is not None and i < len(avail) and not avail[i]:
                self.n_plans_skipped_dead += 1
                continue
            a = int(assign[i])
            if not 0 <= a < len(targets):
                continue
            pose_xy = self._robot_pose_xy(i)
            if pose_xy is None:
                continue
            target = targets[a]
            if a not in fields:
                if plan_lo is None:
                    plan_lo = self._planning_grid()
                fields[a] = P.goal_field(
                    self.cfg.planner, self.cfg.frontier, self.cfg.grid,
                    plan_lo,
                    jnp.asarray(np.asarray(target, np.float32)))
                self.n_goal_fields += 1
            r = P.descend_field(self.cfg.planner, self.cfg.frontier,
                                self.cfg.grid, fields[a],
                                jnp.asarray(np.asarray(target,
                                                       np.float32)),
                                jnp.asarray(pose_xy))
            reachable = bool(r.reachable)
            wp = np.asarray(r.waypoint_xy, np.float32)
            self.fwp_pub.publish(Waypoint(
                header=hdr, x=float(wp[0]), y=float(wp[1]),
                reachable=reachable, goal_x=float(target[0]),
                goal_y=float(target[1]), robot=i))
            self.n_frontier_plans += 1
            M.counters.inc("planner.frontier_plans")
            if i == self.robot_idx:
                # (Robots in manual_robots were skipped above, so this
                # can only be the frontier plan for the /plan robot.)
                path = np.asarray(r.path_xy)[np.asarray(r.path_valid)]
                self.plan_pub.publish(Path(header=hdr, poses_xy=path))

    def status(self) -> dict:
        return {"n_plans": self.n_plans,
                "n_frontier_plans": self.n_frontier_plans,
                "last_reachable": self.last_reachable,
                "reachable_by_robot": dict(self.reachable_by_robot),
                "goal": self._current_goal()}
