"""Minimal grayscale PNG encoder (stdlib zlib only).

Plays PIL's role in the reference's `/map-image` endpoint
(`/root/reference/server/thymio_project/thymio_project/main.py:270-275`)
without a PIL dependency: 8-bit grayscale, one IDAT, fixed spec-compliant
output verified against PIL in tests when PIL is available.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

_SIGNATURE = b"\x89PNG\r\n\x1a\n"


def _chunk(tag: bytes, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload)) + tag + payload
            + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))


def encode_gray(img: np.ndarray, compress_level: int = 6) -> bytes:
    """Encode a (H, W) uint8 array as a grayscale PNG byte string."""
    arr = np.ascontiguousarray(img, np.uint8)
    if arr.ndim != 2:
        raise ValueError(f"expected (H, W) grayscale, got shape {arr.shape}")
    h, w = arr.shape
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # 8-bit gray
    # Filter byte 0 (None) prepended to every row.
    raw = np.empty((h, w + 1), np.uint8)
    raw[:, 0] = 0
    raw[:, 1:] = arr
    idat = zlib.compress(raw.tobytes(), compress_level)
    return (_SIGNATURE + _chunk(b"IHDR", ihdr) + _chunk(b"IDAT", idat)
            + _chunk(b"IEND", b""))


def decode_gray(png: bytes) -> np.ndarray:
    """Decode a grayscale PNG produced by `encode_gray` (tests/round-trip)."""
    if png[:8] != _SIGNATURE:
        raise ValueError("not a PNG")
    pos = 8
    w = h = None
    idat = b""
    while pos < len(png):
        (length,) = struct.unpack(">I", png[pos:pos + 4])
        tag = png[pos + 4:pos + 8]
        payload = png[pos + 8:pos + 8 + length]
        if tag == b"IHDR":
            w, h, depth, color = struct.unpack(">IIBB", payload[:10])
            if depth != 8 or color != 0:
                raise ValueError("decode_gray only handles 8-bit grayscale")
        elif tag == b"IDAT":
            idat += payload
        pos += 12 + length
    raw = np.frombuffer(zlib.decompress(idat), np.uint8).reshape(h, w + 1)
    if np.any(raw[:, 0] != 0):
        raise ValueError("decode_gray only handles filter type 0")
    return raw[:, 1:].copy()
