"""Joystick device source: raw Linux evdev events -> TeleopNode.

Round 3's verdict: `bridge/teleop.py` implements the reference's
joystick.yaml semantics but "no actual /dev/input/evdev event loop feeds
it — a real pad cannot drive the stack today". This is that event loop,
zero-dependency by design (the python-evdev package is not in this image
and the framework vendors nothing): the Linux input event protocol is a
plain struct stream — `struct input_event { struct timeval time; __u16
type; __u16 code; __s32 value; }` — read straight off
`/dev/input/eventN` with stdlib `struct`, exactly how the C++ LD06
driver's framing is handled by `native/ld06.cpp` for the serial stream.

Axis/button model (the `teleop_twist_joy` joy-message convention the
reference's config addresses,
`server/install/.../config/joystick.yaml`):

  * EV_ABS events update an axes array indexed by a code->axis table
    (default: ABS_X..ABS_RZ -> 0..5, hat -> 6/7 — the common gamepad
    enumeration, PS4-over-USB included);
  * values normalize to [-1, 1] from per-axis (min, max) ranges —
    queried from the device via the EVIOCGABS ioctl when the fd is a
    real evdev node, else the PS4-USB default 0..255;
  * vertical stick axes invert so "stick forward" is +1 (the joy-node
    convention the scale_linear sign assumes);
  * EV_KEY events with gamepad/joystick codes (BTN_GAMEPAD 0x130..,
    BTN_JOYSTICK 0x120..) update a buttons array — BTN_SOUTH (the PS4
    X button) lands on index 0, the deadman in joystick.yaml;
  * EV_SYN frames a sample: only then does the assembled state reach
    `TeleopNode.update()` (per-event pushes would tear one physical
    sample into several half-updated ones).

Testing runs the real reader against synthetic spec-conformant byte
streams through a pipe (no /dev/input or uinput exists in CI images) —
the `tests/test_native.py` pattern.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Optional, Tuple

from jax_mapping.bridge.teleop import TeleopNode

# struct input_event with native long timeval: 24 bytes on 64-bit Linux.
EVENT = struct.Struct("llHHi")

EV_SYN, EV_KEY, EV_ABS = 0x00, 0x01, 0x03

# Default code -> axis-index table (gamepad enumeration order).
DEFAULT_AXIS_MAP: Dict[int, int] = {
    0x00: 0,   # ABS_X      left stick horizontal
    0x01: 1,   # ABS_Y      left stick vertical
    0x02: 2,   # ABS_Z      right stick horizontal (PS4 USB)
    0x03: 3,   # ABS_RX
    0x04: 4,   # ABS_RY
    0x05: 5,   # ABS_RZ     right stick vertical (PS4 USB)
    0x10: 6,   # ABS_HAT0X
    0x11: 7,   # ABS_HAT0Y
}
# Vertical axes report "up" as smaller raw values; invert so forward=+1.
DEFAULT_INVERT = frozenset({1, 4, 5, 7})

_N_AXES = 8
_N_BUTTONS = 16


def _eviocgabs(code: int) -> int:
    """ioctl number for EVIOCGABS(code): _IOR('E', 0x40+code,
    struct input_absinfo[24 bytes])."""
    return (2 << 30) | (24 << 16) | (ord("E") << 8) | (0x40 + code)


class JoyDeviceReader:
    """Read evdev events from a device (or any byte stream) into a
    TeleopNode.

    Args:
      source: path to an evdev node ("/dev/input/event3") or an open
        readable file object / fd producing input_event bytes.
      teleop: the TeleopNode whose `update()` receives assembled samples.
      axis_map / invert_axes: code routing (defaults above).
      abs_ranges: {axis_index: (min, max)} normalization overrides; real
        devices are queried via EVIOCGABS instead, non-device sources
        fall back to (0, 255) per stick axis, (-1, 1) per hat.
    """

    def __init__(self, source, teleop: TeleopNode,
                 axis_map: Optional[Dict[int, int]] = None,
                 invert_axes=DEFAULT_INVERT,
                 abs_ranges: Optional[Dict[int, Tuple[float, float]]] = None):
        self.teleop = teleop
        self.axis_map = dict(axis_map or DEFAULT_AXIS_MAP)
        self.invert_axes = frozenset(invert_axes)
        self._axes = [0.0] * _N_AXES
        self._buttons = [0] * _N_BUTTONS
        self._dirty = False
        self.n_samples = 0
        self.n_unknown_events = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        if isinstance(source, (str, os.PathLike)):
            self._fd = os.open(source, os.O_RDONLY)
            self._own_fd = True
        elif isinstance(source, int):
            self._fd = source
            self._own_fd = False
        else:
            self._fd = source.fileno()
            self._own_fd = False

        self._ranges: Dict[int, Tuple[float, float]] = {}
        for code, idx in self.axis_map.items():
            rng = self._query_absinfo(code)
            if rng is None:
                rng = (-1.0, 1.0) if code >= 0x10 else (0.0, 255.0)
            self._ranges[idx] = rng
        if abs_ranges:
            self._ranges.update(abs_ranges)

    def _query_absinfo(self, code: int) -> Optional[Tuple[float, float]]:
        """(min, max) from the device, or None off a non-evdev source."""
        try:
            import fcntl
            buf = bytearray(24)
            fcntl.ioctl(self._fd, _eviocgabs(code), buf)
            _value, lo, hi, _fuzz, _flat, _res = struct.unpack("6i", buf)
            if hi > lo:
                return float(lo), float(hi)
        except OSError:
            pass
        return None

    # -- event pump ---------------------------------------------------------

    def _normalize(self, idx: int, raw: int) -> float:
        lo, hi = self._ranges.get(idx, (0.0, 255.0))
        v = 2.0 * (raw - lo) / (hi - lo) - 1.0
        v = max(-1.0, min(1.0, v))
        return -v if idx in self.invert_axes else v

    def _handle(self, etype: int, code: int, value: int) -> None:
        if etype == EV_ABS and code in self.axis_map:
            self._axes[self.axis_map[code]] = self._normalize(
                self.axis_map[code], value)
            self._dirty = True
        elif etype == EV_KEY and 0x130 <= code < 0x130 + _N_BUTTONS:
            self._buttons[code - 0x130] = 1 if value else 0
            self._dirty = True
        elif etype == EV_KEY and 0x120 <= code < 0x120 + _N_BUTTONS:
            # BTN_JOYSTICK block (flight sticks); same index convention.
            self._buttons[code - 0x120] = 1 if value else 0
            self._dirty = True
        elif etype == EV_SYN:
            if self._dirty:
                self.teleop.update(list(self._axes), list(self._buttons))
                self.n_samples += 1
                self._dirty = False
        else:
            self.n_unknown_events += 1

    def pump(self, max_events: Optional[int] = None) -> int:
        """Read loop; returns after EOF, `close()`, or `max_events`.
        Returns the number of events consumed.

        Reads are gated on a short select() so `close()` can interrupt a
        quiet pad promptly — a bare blocking os.read cannot be woken by
        the stop flag, and closing the fd under it would race fd reuse.
        """
        import select
        n = 0
        buf = b""
        while not self._stop.is_set():
            if max_events is not None and n >= max_events:
                break
            try:
                ready, _, _ = select.select([self._fd], [], [], 0.2)
            except (OSError, ValueError):
                break
            if not ready:
                continue
            try:
                chunk = os.read(self._fd, EVENT.size * 64)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while len(buf) >= EVENT.size:
                _sec, _usec, etype, code, value = EVENT.unpack_from(buf)
                buf = buf[EVENT.size:]
                self._handle(etype, code, value)
                n += 1
        return n

    def spin_thread(self) -> "JoyDeviceReader":
        t = threading.Thread(target=self.pump, daemon=True, name="joydev")
        t.start()
        # Publish only a STARTED thread: assigning before start() would
        # make close() join an unstartable thread if start() raises.
        self._thread = t
        return self

    def close(self) -> None:
        """Stop the pump (select window bounds the wait), then close the
        fd — in that order: closing under a live read would let a reused
        fd number feed unrelated bytes into the event parser."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._own_fd and (self._thread is None
                             or not self._thread.is_alive()):
            try:
                os.close(self._fd)
            except OSError:
                pass


def pack_event(etype: int, code: int, value: int,
               t: float = 0.0) -> bytes:
    """A spec-conformant input_event record (test/emulation helper —
    what a uinput device would produce)."""
    sec = int(t)
    usec = int((t - sec) * 1e6)
    return EVENT.pack(sec, usec, etype, code, value)


class JoystickSession:
    """Owns the teleop chain's lifetime: reader thread + the executor
    that fires TeleopNode's autorepeat timer (a TeleopNode without an
    executor never publishes — timers only run inside Executor.spin)."""

    def __init__(self, teleop: TeleopNode, reader: JoyDeviceReader,
                 executor) -> None:
        self.teleop = teleop
        self.reader = reader
        self.executor = executor

    def close(self) -> None:
        self.reader.close()
        self.executor.shutdown()


def attach_joystick(bus, device_path: str, cfg=None) -> JoystickSession:
    """One-call bring-up: TeleopNode + its own executor + reader thread.

    The operator-facing entry (`jax-mapping-ros --joy-device
    /dev/input/event<N>`); returns a JoystickSession the caller closes.
    """
    from jax_mapping.bridge.node import Executor

    teleop = TeleopNode(bus, cfg)
    # Open the device BEFORE starting the executor: a bad --joy-device
    # path raises from JoyDeviceReader.__init__, and an already-spinning
    # executor thread + TeleopNode subscription would leak for the
    # process lifetime when the caller catches that error.
    reader = JoyDeviceReader(device_path, teleop)
    try:
        executor = Executor([teleop])
        executor.spin_thread()
        try:
            reader.spin_thread()
        except BaseException:
            executor.shutdown()
            raise
    except BaseException:
        reader.close()
        raise
    return JoystickSession(teleop, reader, executor)
