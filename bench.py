"""Benchmark: LiDAR scan fusion throughput into the full-size 4096^2 grid.

Headline metric per BASELINE.md: >= 50,000 scans/sec fused into a 4096^2
0.05 m log-odds grid on a v5e-8. This runs on whatever devices are visible
(the driver provides one real chip) and pro-rates the baseline target by
device count: vs_baseline = scans_per_sec / (50_000 * n_devices / 8).

Also measures frontier recompute latency at 64 robots (target < 5 ms p50)
in BOTH cost modes: `frontier_p50_ms_64robots` is the product default
(obstacle-aware BFS costs, config.py FrontierConfig.obstacle_aware=True);
`frontier_euclid_p50_ms_64robots` is the cheap Euclidean mode. The
PUBLISH-path comparison (full recompute vs the incremental
revision-keyed pipeline) is its own suite: `--suite frontier`
(BENCH_FRONTIER_r*.json; host-driven per-publish methodology — not
comparable to the chain p50s above).

Round-1 lesson (VERDICT.md): the bench must emit its JSON line inside the
driver budget no matter what the toolchain does. Three guards:

  1. Backend probe in a BOUNDED SUBPROCESS before anything compiles — the
     TPU tunnel in this image can hang backend init indefinitely (even
     `jax.devices()`), which no in-process deadline can interrupt; round 5
     also saw a half-wedged state where enumeration answers in ~1 s but
     every compile RPC blocks, so the probe jit-compiles a scalar too
     (utils/backend_guard.py). If the probe fails, re-exec once onto
     scrubbed virtual-CPU before burning any compile time, and say so in
     the JSON ("platform" field).
  2. A watchdog thread with a hard deadline (JAX_MAPPING_BENCH_DEADLINE_S,
     default 540 s) that prints whatever sections completed and exits —
     partial data over rc 124.
  3. Pallas failures fall back to the parity-tested XLA paths IN PROCESS
     (flip JAX_MAPPING_NO_PALLAS and re-trace) — no full-process re-exec.

Methodology — honest device-side timing. On the tunneled TPU platform used
here, any host-synchronising fetch pays a large fixed round-trip. So each
workload is timed as a `lax.fori_loop` chain of K data-dependent iterations
inside ONE jit, synchronised by fetching a scalar, at two chain lengths
K1 < K2; per-iteration device time = (t(K2) - t(K1)) / (K2 - K1), which
cancels the fixed dispatch + fetch overhead exactly. This is the device
kernel throughput the BASELINE targets describe (on-pod there is no tunnel
RTT). If host jitter inverts the difference, fall back to t(K2)/K2 — an
upper bound that errs against us.

Prints exactly ONE JSON line (plus diagnostics on stderr).
"""

import json
import math
import os
import sys
import threading
import time

import numpy as np

DEADLINE_S = float(os.environ.get("JAX_MAPPING_BENCH_DEADLINE_S", "540"))
PROBE_TIMEOUT_S = float(os.environ.get("JAX_MAPPING_BENCH_PROBE_S", "120"))

_T0 = time.monotonic()
_RESULT = {
    "metric": "lidar_scan_fusion_throughput",
    "value": None,
    "unit": "scans/sec into 4096^2 0.05m grid",
    "vs_baseline": None,
    "devices": "unknown",
    "frontier_p50_ms_64robots": None,
    "frontier_euclid_p50_ms_64robots": None,
    "match_p50_ms": None,
    "slam_step_p50_ms": None,
    "fleet_tick_p50_ms_8robots": None,
    "fleet_tick_p50_ms_64robots": None,
    # Global replan latency at production scale (ops/planner.plan_to_goal:
    # 4096^2 map -> coarse goal-seeded BFS + descent). Budget: one replan
    # per PlannerConfig.period_s (1000 ms) per goal robot.
    "plan_p50_ms": None,
    "voxel_images_per_sec": None,
    # Shared-patch window fast path (voxel_kernel.window_delta); TPU only.
    "voxel_window_images_per_sec": None,
    # Engine voxel fuse_depths dispatched to (pallas on TPU, else xla).
    "voxel_path": None,
    "path": None,
    # Engine actually used by the frontier cost fields ("pallas" unless
    # the probe or the production-shape run rejected the kernel).
    "costfield_path": None,
    "sections_completed": [],
    # Budget-aware scheduling (r06): sections that did NOT run, keyed to
    # why — starvation is a recorded fact, not a silent absence
    # (BENCH_r05 silently skipped fleet_tick_* and plan).
    "sections_skipped": {},
    # Host/toolchain identity: round-over-round comparisons are only
    # meaningful when the JSON says what produced the number (VERDICT r4).
    "provenance": None,
}
_EMITTED = threading.Event()

# =========================================================================
# BenchRecord schema (ISSUE 10): the eleven BENCH_*.json one-liners are
# the repo's perf trajectory, but until now they shared no schema and
# gated nothing. Every suite now emits a VERSIONED record (bench_schema,
# suite, metric, methodology, provenance, reps), `--validate` schema-
# checks every committed BENCH_*.json (grandfathering the pre-schema
# fields — history is data, not a liability), and `--regress` compares a
# fresh run of the regress micro-suite against the committed trajectory
# with noise-aware ratio gates and exits non-zero on regression.
# Everything in this block runs WITHOUT importing jax (the --validate
# path must start fast; tier-1 wires it into the analysis selfcheck).
# =========================================================================

BENCH_SCHEMA_VERSION = 1

_MAIN_METHODOLOGY = (
    "per-iteration device time from lax.fori_loop chains at two traced "
    "lengths, (t(K2)-t(K1))/(K2-K1) (cancels fixed dispatch+fetch "
    "overhead); falls back to t(K2)/K2 when host jitter inverts the "
    "difference — see the module docstring")

_REGRESS_METHODOLOGY = (
    "median-of-reps wall time over a fixed calls_per_rep batch with a "
    "block_until_ready barrier (sub-10ms workloads are batched into "
    "the stable tens-of-ms regime), ALTERNATING A/B between each "
    "workload and a fixed numpy reference op (the PR 8 "
    "wall-clock-noise gotcha: this builder's clock drifts tens of "
    "percent minutes apart, so the gate compares reference-NORMALIZED "
    "ratios, not raw milliseconds)")


def _stamp_record(result: dict, suite: str, methodology: str = None,
                  reps=None) -> None:
    """Stamp the versioned BenchRecord fields onto a suite's result
    dict (setdefault: a suite that already says who it is wins)."""
    result.setdefault("bench_schema", BENCH_SCHEMA_VERSION)
    result.setdefault("suite", suite)
    if methodology is not None:
        result.setdefault("methodology", methodology)
    if reps is not None:
        result.setdefault("reps", reps)


def extract_bench_record(doc):
    """(record, wrapped): unwrap a driver-captured file ({n, cmd, rc,
    tail} with the JSON line inside `tail`) to the record itself, or
    pass a bare record through. record is None when a wrapped file
    holds no parseable JSON object line (a failed round)."""
    if isinstance(doc, dict) and "tail" in doc and "cmd" in doc:
        for line in reversed(str(doc.get("tail", "")).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line), True
                except ValueError:
                    continue
        return None, True
    return doc, False


def validate_bench_record(rec, path: str, wrapped: bool,
                          raw: dict = None) -> list:
    """Schema errors for one record. Versioned records (bench_schema
    present) must carry suite/metric/methodology strings and sane
    provenance/reps types; pre-schema records are grandfathered down
    to 'has a metric (or is an annotated note)'; a wrapped file with
    no record at all is acceptable only when the captured run itself
    failed (rc != 0) — that IS the trajectory saying the round died."""
    errs = []
    name = os.path.basename(path)
    if rec is None:
        if not wrapped or (raw or {}).get("rc", 0) == 0:
            errs.append(f"{name}: no parseable benchmark record")
        return errs
    if not isinstance(rec, dict):
        return [f"{name}: record is not a JSON object"]
    v = rec.get("bench_schema")
    if v is None:
        if "metric" not in rec and "note" not in rec:
            errs.append(f"{name}: pre-schema record without a 'metric' "
                        "(or annotated 'note') field")
        return errs
    if v != BENCH_SCHEMA_VERSION:
        errs.append(f"{name}: unsupported bench_schema {v!r}")
        return errs
    for key in ("suite", "metric", "methodology"):
        if not isinstance(rec.get(key), str) or not rec.get(key):
            errs.append(f"{name}: bench_schema={v} record needs a "
                        f"non-empty string '{key}'")
    prov = rec.get("provenance")
    if prov is not None and not isinstance(prov, dict):
        errs.append(f"{name}: 'provenance' must be an object or null")
    reps = rec.get("reps")
    if reps is not None and not isinstance(reps, int):
        errs.append(f"{name}: 'reps' must be an integer or null")
    return errs


def validate_bench_records(root: str = None):
    """(n_files, errors) over every committed BENCH_*.json at the repo
    root — the `--validate` / tier-1 selfcheck surface."""
    import glob
    root = root or os.path.dirname(os.path.abspath(__file__))
    errors = []
    files = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    for path in files:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{os.path.basename(path)}: unreadable "
                          f"({e})")
            continue
        rec, wrapped = extract_bench_record(doc)
        errors.extend(validate_bench_record(
            rec, path, wrapped, raw=doc if wrapped else None))
    return len(files), errors


def _validate_main() -> None:
    n, errors = validate_bench_records()
    for e in errors:
        print(e, file=sys.stderr)
    print(json.dumps({"suite": "validate", "n_records": n,
                      "n_errors": len(errors), "errors": errors}),
          flush=True)
    sys.exit(1 if errors else 0)


# -------------------------------------------------------- regress gate

#: Calls per timed rep for sub-10ms workloads (see fuse_tiny note).
REGRESS_CALLS_PER_REP = {"fuse_tiny": 16, "match_tiny": 1}


def run_regress_suite(reps: int = 5,
                      synthetic_slow_ms: float = 0.0) -> dict:
    """Run the regress micro-suite and return its BenchRecord.

    Workloads are tiny-config repo hot paths (window fusion, the
    branch-and-bound matcher) timed per call with a device barrier;
    each rep ALTERNATES workload / reference (a fixed numpy matmul
    chain), so host-speed drift moves both and the `--regress` gate
    can compare reference-normalized ratios across machines and
    minutes. `synthetic_slow_ms` injects a seeded synthetic slowdown
    into the WORKLOAD timing only — the harness self-test hook the
    regression-detection test uses (also reachable via the
    JAX_MAPPING_BENCH_SYNTHETIC_SLOWDOWN_MS env var)."""
    import jax
    import jax.numpy as jnp

    from jax_mapping.config import tiny_config
    from jax_mapping.ops import grid as G
    from jax_mapping.ops import scan_match as M

    cfg = tiny_config()
    g, s = cfg.grid, cfg.scan
    rng = np.random.default_rng(0)
    ranges = rng.uniform(0.5, 2.5, (4, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    poses = np.zeros((4, 3), np.float32)
    ranges_d = jnp.asarray(ranges)
    poses_d = jnp.asarray(poses)
    grid0 = G.empty_grid(g)
    grid_w = G.fuse_scans_window(g, s, grid0, ranges_d, poses_d)
    jax.block_until_ready(grid_w)
    guess = jnp.zeros(3, jnp.float32)

    # A single tiny fusion is ~2 ms on this builder and swings 4x
    # run-to-run (scheduler quanta dominate); each timed rep covers a
    # fixed BATCH of calls so the measurement sits in the stable
    # tens-of-ms regime match_tiny already occupies. calls_per_rep is
    # stamped into the record — ratios against a record taken at a
    # different batch size are meaningless and the gate refuses them.
    def fuse_tiny():
        for _ in range(REGRESS_CALLS_PER_REP["fuse_tiny"]):
            jax.block_until_ready(
                G.fuse_scans_window(g, s, grid0, ranges_d, poses_d))

    def match_tiny():
        jax.block_until_ready(
            M.match(g, s, cfg.matcher, grid_w, ranges_d[0], guess).pose)

    ref_a = np.random.default_rng(1).standard_normal(
        (256, 256)).astype(np.float32)

    def reference():
        b = ref_a
        for _ in range(24):
            b = b @ ref_a
        return float(b[0, 0])

    workloads = {}
    for name, fn in (("fuse_tiny", fuse_tiny),
                     ("match_tiny", match_tiny)):
        fn()                                   # compile + warm
        reference()
        w_ts, r_ts = [], []
        for _ in range(reps):                  # alternating A/B
            t0 = time.perf_counter()
            fn()
            if synthetic_slow_ms > 0:
                time.sleep(synthetic_slow_ms / 1e3)
            w_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            reference()
            r_ts.append(time.perf_counter() - t0)
        workloads[name] = {
            "p50_ms": round(float(np.median(w_ts)) * 1e3, 3),
            "ref_p50_ms": round(float(np.median(r_ts)) * 1e3, 4),
            "calls_per_rep": REGRESS_CALLS_PER_REP.get(name, 1),
        }
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    import jax as _jax
    return {
        "bench_schema": BENCH_SCHEMA_VERSION, "suite": "regress",
        "metric": "regress_suite_p50_ms",
        "methodology": _REGRESS_METHODOLOGY, "reps": reps,
        "workloads": workloads,
        "synthetic_slow_ms": synthetic_slow_ms,
        "provenance": {
            "cpu_count": os.cpu_count(), "loadavg_1m": load1,
            "jax": _jax.__version__,
            "python": ".".join(map(str, sys.version_info[:3]))},
    }


#: Default regression gate. A workload regresses only when BOTH its
#: raw fresh/committed p50 ratio AND its reference-NORMALIZED ratio
#: exceed the gate: a slower host inflates the raw ratio but not the
#: normalized one, and a noisy reference measurement inflates the
#: normalized ratio but not the raw one — a real pipeline regression
#: inflates both. 1.8x sits comfortably above this builder's measured
#: run-to-run noise (tens of percent, PR 8 gotcha) while the seeded
#: self-test's synthetic slowdown (~4x) clears it on both axes.
REGRESS_GATE = 1.8


def compare_regress(fresh: dict, committed: dict,
                    gate: float = REGRESS_GATE):
    """(ok, report_lines): per shared workload, regression iff
    min(raw ratio, reference-normalized ratio) > gate."""
    lines = []
    ok = True
    fw = (fresh or {}).get("workloads") or {}
    cw = (committed or {}).get("workloads") or {}
    shared = sorted(set(fw) & set(cw))
    if not shared:
        return False, ["no comparable workloads between the fresh run "
                       "and the committed trajectory"]
    for name in shared:
        f, c = fw[name], cw[name]
        if f.get("calls_per_rep", 1) != c.get("calls_per_rep", 1):
            ok = False
            lines.append(f"{name}: calls_per_rep mismatch "
                         f"({f.get('calls_per_rep', 1)} vs "
                         f"{c.get('calls_per_rep', 1)}) — re-record the "
                         f"trajectory, ratios across batch sizes are "
                         f"meaningless")
            continue
        try:
            raw = f["p50_ms"] / c["p50_ms"]
            norm = (f["p50_ms"] / f["ref_p50_ms"]) \
                / (c["p50_ms"] / c["ref_p50_ms"])
        except (KeyError, TypeError, ZeroDivisionError):
            ok = False
            lines.append(f"{name}: unreadable timing fields")
            continue
        regressed = min(raw, norm) > gate
        if regressed:
            ok = False
        lines.append(
            f"{name}: fresh {f['p50_ms']}ms (ref {f['ref_p50_ms']}ms) "
            f"vs committed {c['p50_ms']}ms (ref {c['ref_p50_ms']}ms) "
            f"-> raw x{raw:.2f}, normalized x{norm:.2f} "
            f"[{'REGRESSION' if regressed else 'ok'}, gate x{gate}]")
    return ok, lines


def newest_committed_regress(root: str = None):
    """The newest committed BENCH_REGRESS_r*.json record, or None."""
    import glob
    root = root or os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(
            os.path.join(root, "BENCH_REGRESS_r*.json")), reverse=True):
        try:
            with open(path) as f:
                rec, _ = extract_bench_record(json.load(f))
            if rec is not None:
                return rec
        except (OSError, ValueError):
            continue
    return None


def _regress_main() -> None:
    """`bench.py --regress` — the gated bench-regression harness: run
    the regress micro-suite fresh and compare against the committed
    trajectory (newest BENCH_REGRESS_r*.json) with the reference-
    normalized ratio gate. Exit 0 clean, 1 on regression, 2 when no
    committed trajectory exists. CPU-pinned like the serving/frontier
    suites (the workloads are tiny host-driven dispatches; a wedged
    TPU tunnel must not hang the gate)."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))

    def _flag(flag, default):
        if flag in sys.argv:
            i = sys.argv.index(flag)
            if i + 1 < len(sys.argv):
                return sys.argv[i + 1]
        return default

    gate = float(_flag("--gate", REGRESS_GATE))
    reps = int(_flag("--reps", 5))
    slow_ms = float(os.environ.get(
        "JAX_MAPPING_BENCH_SYNTHETIC_SLOWDOWN_MS", "0"))
    committed = newest_committed_regress()
    result = {"suite": "regress", "error": "watchdog deadline hit"}
    emitted = threading.Event()

    def emit(code: int = 0) -> None:
        if not emitted.is_set():
            emitted.set()
            print(json.dumps(result), flush=True)
            out = _flag("--out", None)
            if out:
                try:
                    with open(out, "w") as f:
                        f.write(json.dumps(result) + "\n")
                except OSError:
                    pass
        os._exit(code)

    # Deadline = error (2), NOT clean: --regress's exit code is a gate
    # (0 clean / 1 regression / 2 error) — a wedged run that never
    # compared anything must not report "no regression".
    watchdog = threading.Timer(max(_remaining(), 1.0), emit, args=(2,))
    watchdog.daemon = True
    watchdog.start()
    try:
        result = run_regress_suite(reps=reps, synthetic_slow_ms=slow_ms)
        if committed is None:
            result["regress"] = {"ok": None, "gate": gate, "report": [
                "no committed BENCH_REGRESS_r*.json trajectory — "
                "commit this run's record first (--out)"]}
            print("bench[regress]: no committed trajectory",
                  file=sys.stderr, flush=True)
            emit(2)
        ok, report = compare_regress(result, committed, gate=gate)
        result["regress"] = {"ok": ok, "gate": gate, "report": report}
        for line in report:
            print(f"bench[regress]: {line}", file=sys.stderr, flush=True)
        emit(0 if ok else 1)
    except Exception:
        import traceback
        traceback.print_exc(file=sys.stderr)
        result = {"suite": "regress",
                  "error": "regress suite failed (see stderr)"}
        emit(2)


def _skip_section(key: str, why: str) -> None:
    _RESULT["sections_skipped"][key] = why
    print(f"bench: skipping {key} ({why})", file=sys.stderr, flush=True)


def _emit_and_exit(code: int = 0) -> None:
    if not _EMITTED.is_set():
        _EMITTED.set()
        _stamp_record(_RESULT, "main", _MAIN_METHODOLOGY)
        print(json.dumps(_RESULT), flush=True)
    os._exit(code)


def _remaining() -> float:
    return DEADLINE_S - (time.monotonic() - _T0)


def _scrub_cpu_env() -> dict:
    # Shared guard (utils/backend_guard.py — the same scrub demo.py and
    # jax-mapping-ros use) plus two bench-specific keys: the legacy bench
    # flag the JSON labelling reads, and the deadline re-budget — the
    # re-exec'd process restarts its deadline clock; hand it only the
    # budget this process has left, or the probe's 120 s + a fresh 540 s
    # watchdog would overshoot the caller's own timeout and the round
    # would end with NO JSON line at all (the round-1 failure mode).
    from jax_mapping.utils.backend_guard import scrubbed_cpu_env
    return scrubbed_cpu_env(extra_env={
        "_JAX_MAPPING_BENCH_CPU_FALLBACK": "1",
        "JAX_MAPPING_BENCH_DEADLINE_S": str(max(60.0, _remaining())),
    })


def _probe_backend() -> bool:
    from jax_mapping.utils.backend_guard import backend_probe_ok
    return backend_probe_ok(timeout_s=PROBE_TIMEOUT_S)


def _serving_main() -> None:
    """`bench.py --suite serving` — the map-serving benchmark
    (serving/loadgen.py): N concurrent synthetic clients against a live
    `launch_sim_stack`, whole-PNG polling vs the tiled delta protocol.
    Prints exactly ONE JSON line, same contract as the kernel bench.

    A host/stack benchmark: pinned to virtual CPU (the sim stack's jit
    compiles must not hang on a wedged TPU tunnel, and the serving
    numbers measure HTTP bytes and host encode work, not device
    kernels)."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {"metric": "map_serving_bytes_per_client",
              "suite": "serving", "error": "watchdog deadline hit"}
    emitted = threading.Event()

    def emit(code: int = 0) -> None:
        if not emitted.is_set():
            emitted.set()
            _stamp_record(result, "serving",
                          "N concurrent synthetic clients against a "
                          "live launch_sim_stack: whole-PNG polling "
                          "vs the tiled delta protocol, HTTP bytes "
                          "and host encode work (serving/loadgen.py)")
            print(json.dumps(result), flush=True)
        os._exit(code)

    watchdog = threading.Timer(max(_remaining(), 1.0), emit)
    watchdog.daemon = True
    watchdog.start()
    out = None
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        out = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
    try:
        from jax_mapping.serving.loadgen import run_serving_benchmark
        result = run_serving_benchmark(out_path=out)
        try:
            load1 = round(os.getloadavg()[0], 1)
        except OSError:
            load1 = None
        result["provenance"] = {
            "cpu_count": os.cpu_count(), "loadavg_1m": load1,
            "python": ".".join(map(str, sys.version_info[:3]))}
    except Exception:
        import traceback
        traceback.print_exc(file=sys.stderr)
        result["error"] = "serving benchmark failed (see stderr)"
    emit(0)


def main() -> None:
    if "--validate" in sys.argv:
        # Schema-check the committed BENCH_*.json trajectory — no jax
        # import, fast start (tier-1 wires this into the analysis
        # selfcheck).
        _validate_main()
        return
    if "--regress" in sys.argv:
        _regress_main()
        return
    if "--suite" in sys.argv:
        i = sys.argv.index("--suite")
        suite = sys.argv[i + 1] if i + 1 < len(sys.argv) else ""
        if suite == "serving":
            _serving_main()
            return
        if suite == "match":
            _match_main()
            return
        if suite == "frontier":
            _frontier_main()
            return
        if suite == "obs":
            _obs_main()
            return
        if suite == "fuse":
            _fuse_main()
            return
        if suite == "restart":
            _restart_main()
            return
        if suite == "tenant":
            _tenant_main()
            return
        if suite == "world":
            _world_main()
            return
        print(f"bench: unknown suite {suite!r} "
              "(available: serving, match, frontier, obs, fuse, "
              "restart, tenant, world; also: --validate, --regress)",
              file=sys.stderr, flush=True)
        sys.exit(2)
    if os.environ.get("_JAX_MAPPING_BENCH_CPU_FALLBACK") != "1" \
            and not _probe_backend():
        print("bench: backend init/compile probe did not finish in "
              f"{PROBE_TIMEOUT_S:.0f}s "
              "(wedged TPU tunnel?); falling back to virtual CPU",
              file=sys.stderr, flush=True)
        env = _scrub_cpu_env()
        os.execvpe(sys.executable, [sys.executable] + sys.argv, env)

    watchdog = threading.Timer(max(_remaining(), 1.0),
                               lambda: _emit_and_exit(0))
    watchdog.daemon = True
    watchdog.start()
    try:
        _run()
    except Exception:
        import traceback
        traceback.print_exc(file=sys.stderr)
    _emit_and_exit(0)


def _argv_value(flag: str):
    if flag in sys.argv:
        i = sys.argv.index(flag)
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


_RESTART_METHODOLOGY = (
    "supervisor kill->resume->first-fused-scan wall time, executed in "
    "FRESH subprocesses so jit caches are genuinely cold (the "
    "in-process tier-1 restarts inherit the warm process cache and "
    "cannot see the restart compile storm): one seed child populates "
    "the checkpoint + persistent compile cache + AOT snapshots, then "
    "cold children (cache root recreated empty per rep) and warm "
    "children (populated root) launch identical stacks with "
    "prewarm_on_launch off and time restart_mapper() -> first fused "
    "scan — the staged restore + priority-ordered pre-warm + "
    "readiness gate + drive, which is exactly the restart path and "
    "places the warm tier's own pre-warm cost INSIDE the measured "
    "span; medians over reps, speedup = cold_p50 / warm_p50; "
    "process-boot/launch totals are reported alongside as "
    "total_*_s (they are identical fixed cost in both modes). On "
    "this CPU builder the AOT tier degrades by design (XLA:CPU "
    "executables do not deserialize cross-process) and the "
    "persistent cache carries the speedup; aot counters in "
    "warm_detail record the degradation")


def _restart_main() -> None:
    """`bench.py --suite restart` — the ISSUE 12 gate: supervisor
    kill→resume→first-fused-scan wall time, cold vs warm compile
    caches. Prints exactly ONE JSON line; `--out FILE` copies it (the
    BENCH_RESTART_r* artifact).

    CPU-pinned like the serving suite: the number is host wall time
    over subprocess stacks, and a wedged TPU tunnel must not hang the
    children's backend init."""
    if "--phase" in sys.argv:
        # Child mode (spawned by the orchestrator below, already
        # CPU-pinned via its env): run one seed/resume phase, print one
        # JSON line.
        _restart_phase_main()
        return
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {"metric": "restart_kill_resume_first_fuse_speedup",
              "suite": "restart", "value": None,
              "cold_resume_s_p50": None, "warm_resume_s_p50": None,
              "cold_resume_s": [], "warm_resume_s": [],
              "grid_hash_equal": None, "seed": None, "warm_detail": None,
              "sections_completed": [], "provenance": None,
              "methodology": _RESTART_METHODOLOGY,
              "error": "watchdog deadline hit"}
    _run_suite_guarded(result, _restart_run)


def _restart_run(result: dict) -> None:
    import shutil
    import subprocess
    import tempfile
    result.pop("error", None)
    d = tempfile.mkdtemp(prefix="jm_restart_bench_")
    from jax_mapping.utils.backend_guard import scrubbed_cpu_env
    env = scrubbed_cpu_env(extra_env={"JAX_PLATFORMS": "cpu"})
    # Children must import the repo the orchestrator runs from.
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__)) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")

    def child(phase: str, mode: str = "") -> dict:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--suite", "restart", "--phase", phase, "--dir", d]
        if mode:
            cmd += ["--mode", mode]
        t0 = time.monotonic()
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=max(_remaining() - 15.0, 30.0))
        wall = time.monotonic() - t0
        rec = None
        for line in reversed(p.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                    break
                except ValueError:
                    continue
        if rec is None:
            sys.stderr.write(p.stderr[-4000:])
            raise RuntimeError(
                f"restart child {phase}/{mode} emitted no JSON "
                f"(rc {p.returncode})")
        rec["wall_s"] = round(wall, 3)
        return rec

    seed = child("seed")
    result["seed"] = seed
    result["sections_completed"].append("seed")
    cold, warm = [], []
    hashes = []
    warm_detail = None
    for rep in range(2):
        # Cold = an EMPTY cache root, recreated per rep (the first cold
        # child itself repopulates it).
        shutil.rmtree(os.path.join(d, "cold_cache"), ignore_errors=True)
        c = child("resume", "cold")
        cold.append(c["resume_s"])
        hashes.append(c["grid_hash"])
        result["sections_completed"].append(f"cold_{rep}")
        w = child("resume", "warm")
        warm.append(w["resume_s"])
        hashes.append(w["grid_hash"])
        warm_detail = w
        result["sections_completed"].append(f"warm_{rep}")
        # One rep pair is the floor; the second runs only inside the
        # remaining watchdog budget.
        if rep == 0 and _remaining() < (c["wall_s"] + w["wall_s"]) * 1.6:
            break
    result["cold_resume_s"] = cold
    result["warm_resume_s"] = warm
    result["cold_resume_s_p50"] = round(float(np.median(cold)), 3)
    result["warm_resume_s_p50"] = round(float(np.median(warm)), 3)
    result["value"] = round(result["cold_resume_s_p50"]
                            / max(result["warm_resume_s_p50"], 1e-9), 3)
    # Bit-identity across the warm/cold twins: same checkpoint, same
    # seed, same steps — the fallback ladder must not perturb the map.
    result["grid_hash_equal"] = len(set(hashes)) == 1
    result["warm_detail"] = {
        k: warm_detail.get(k) for k in
        ("import_s", "launch_s", "restart_s", "resume_s", "total_s",
         "steps_to_first_fuse", "warmup", "cache")} \
        if warm_detail else None
    result["total_cold_s"] = c.get("total_s")
    result["total_warm_s"] = (warm_detail or {}).get("total_s")
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "python": ".".join(map(str, sys.version_info[:3]))}
    shutil.rmtree(d, ignore_errors=True)


def _restart_phase_main() -> None:
    """One restart-bench child: `--phase seed` populates checkpoint +
    caches + AOT snapshots; `--phase resume --mode cold|warm` times the
    kill→resume→first-fused-scan path. Exactly one JSON line on
    stdout; stack chatter goes to stderr."""
    import contextlib
    t0 = time.perf_counter()
    phase = _argv_value("--phase")
    d = _argv_value("--dir")
    mode = _argv_value("--mode") or "warm"
    try:
        with contextlib.redirect_stdout(sys.stderr):
            out = _restart_phase(phase, d, mode, t0)
    except Exception as e:                          # noqa: BLE001
        import traceback
        traceback.print_exc(file=sys.stderr)
        out = {"phase": phase, "mode": mode, "error": str(e)}
    print(json.dumps(out), flush=True)


def _grid_hash(stack) -> str:
    import hashlib
    arr = np.asarray(stack.mapper.merged_grid())
    return hashlib.blake2b(np.ascontiguousarray(arr).tobytes(),
                           digest_size=8).hexdigest()


def _restart_phase(phase: str, d: str, mode: str, t0: float) -> dict:
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.config import (ColdStartConfig, DevProfConfig,
                                    ObsConfig, tiny_config)
    from jax_mapping.sim import world as W
    ckpt = os.path.join(d, "ckpt")
    cache = os.path.join(
        d, "cache" if (phase == "seed" or mode == "warm")
        else "cold_cache")
    cfg = tiny_config(n_robots=2).replace(
        # prewarm_on_launch off: the resume children must pay the warm
        # tier INSIDE the measured restart span, not hide it in launch.
        cold_start=ColdStartConfig(enabled=True, cache_dir=cache,
                                   prewarm_on_launch=False),
        # devprof captures the (function, signature) registry the AOT
        # snapshot pass serializes.
        obs=ObsConfig(devprof=DevProfConfig(enabled=True)))
    world = W.plank_course(96, cfg.grid.resolution_m, n_planks=4, seed=3)
    import_s = round(time.perf_counter() - t0, 3)
    st = launch_sim_stack(cfg, world, n_robots=2, http_port=None,
                          realtime=False, seed=3, checkpoint_dir=ckpt)
    launch_s = round(time.perf_counter() - t0, 3)
    try:
        if phase == "seed":
            st.brain.start_exploring()
            st.run_steps(20)
            st.save_auto_checkpoint()
            aot = st.save_compile_snapshots()
            return {"phase": "seed", "import_s": import_s,
                    "launch_s": launch_s,
                    "total_s": round(time.perf_counter() - t0, 3),
                    "aot": aot,
                    "grid_hash": _grid_hash(st)}
        # The kill→resume path: the staged supervisor restart (restore
        # from the seed checkpoint + priority-ordered pre-warm +
        # readiness gate), then step until the first scan fuses. The
        # measured span STARTS at the restart — the jit caches of this
        # fresh process are cold, exactly what the restarted entry
        # points face — and covers the pre-warm cost in both modes.
        st.brain.start_exploring()
        t_kill = time.perf_counter()
        st.restart_mapper()
        restart_s = round(time.perf_counter() - t_kill, 3)
        base = st.mapper.n_scans_fused
        steps = 0
        while st.mapper.n_scans_fused <= base and steps < 60:
            st.run_steps(1)
            steps += 1
        resume_s = round(time.perf_counter() - t_kill, 3)
        return {"phase": "resume", "mode": mode,
                "import_s": import_s, "launch_s": launch_s,
                "restart_s": restart_s, "resume_s": resume_s,
                "total_s": round(time.perf_counter() - t0, 3),
                "steps_to_first_fuse": steps,
                "warmup": (st.warmup.snapshot()["report"]
                           if st.warmup is not None else None),
                "cache": (st.compile_cache.status()
                          if st.compile_cache is not None else None),
                "grid_hash": _grid_hash(st)}
    finally:
        st.shutdown()


def _match_main() -> None:
    """`bench.py --suite match` — the scan-matcher micro-suite: the
    SAME production-config match workload timed through the exhaustive
    sweep (`MatcherConfig.pruned=False`, the pre-pruning pipeline) and
    the branch-and-bound path, plus the host-driven cached pyramid
    path's steady-state hit rate. Prints exactly ONE JSON line; `--out
    FILE` additionally writes it to FILE (the BENCH_MATCH_r* artifact).

    Runs on whatever backend the main bench would use (same bounded
    probe + virtual-CPU fallback + watchdog): the comparison is
    same-host by construction — both paths share the grid, the scan,
    and the chain-timing methodology."""
    if os.environ.get("_JAX_MAPPING_BENCH_CPU_FALLBACK") != "1" \
            and not _probe_backend():
        print("bench[match]: backend probe failed; falling back to "
              "virtual CPU", file=sys.stderr, flush=True)
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   _scrub_cpu_env())
    result = {"metric": "scan_match_p50_ms", "suite": "match",
              "exhaustive_p50_ms": None, "pruned_p50_ms": None,
              "speedup": None, "pyramid_cache_hit_rate": None,
              "pyramid_build_ms": None, "devices": "unknown",
              "sections_completed": [], "provenance": None}
    _run_suite_guarded(result, _match_run)


def _tenant_main() -> None:
    """`bench.py --suite tenant` — mission multi-tenancy (ISSUE 14):
    aggregate mission-steps/sec for 1/4/16/32 independent micro
    missions MEGABATCHED through one `tenancy.megabatch_step` dispatch
    chain per tick, against the same missions ticked sequentially.
    Two sequential baselines are reported side by side, never hidden
    in an average:

    * `sequential_stack_ms_per_mission_step` — each mission as its own
      deployed solo stack (`launch_sim_stack`: its own mapping-
      pipeline dispatches PLUS its own host-side tick loop — the
      per-mapper form whose ~10 ms/tick BENCH_OBS_r02 measured and the
      tenancy motivation cites), ticked one mission after another.
      The headline speedup (`value`) reads against this, the form a
      mission actually runs as today.
    * `sequential_dispatch_ms_per_mission_step` — the bare solo
      `fleet_step`-per-mission floor (no host loop at all): the
      strictest apples-to-apples bound on what batching the device
      work alone buys on this backend.

    CPU-pinned; BOTH sides are timed host-driven per call with a
    device barrier per tick — never the fori_loop chain form (the
    PR 5 CPU-conv gotcha). Prints exactly ONE JSON line; `--out FILE`
    additionally writes it (the BENCH_TENANT_r* artifact)."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {
        "metric": "tenant_megabatch_speedup_32", "suite": "tenant",
        "value": None,
        "tenant_counts": [1, 4, 16, 32],
        "mission_steps_per_point": None,
        "megabatch_ms_per_mission_step": {},
        "megabatch_agg_steps_per_s": {},
        "sequential_stack_ms_per_mission_step": None,
        "sequential_dispatch_ms_per_mission_step": None,
        "speedup_32_vs_stack": None,
        "speedup_32_vs_dispatch": None,
        "bucket_variants_compiled": None,
        "sentinel_overhead": None,
        "methodology": (
            "host-driven per-call wall time with a device barrier per "
            "tick on BOTH sides (never a fori_loop chain — the PR 5 "
            "CPU-conv gotcha). sequential_stack = each mission as its "
            "own deployed solo stack (launch_sim_stack: own mapping "
            "dispatches + own host-side tick loop, the BENCH_OBS_r02 "
            "per-mapper form), ticked one after another; "
            "sequential_dispatch = bare solo fleet_step per mission "
            "per tick, no host loop; megabatch = ONE "
            "TenantControlPlane.step per tick (one dispatch chain + "
            "one host pass for all tenants). The headline value is "
            "speedup_32_vs_stack; speedup_32_vs_dispatch is reported "
            "alongside and is much smaller on CPU (vmapped per-tenant "
            "compute amortizes ~2-3x here; the host tick loop is what "
            "megabatching removes — on TPU the compute axis "
            "vectorizes too). sentinel_overhead = two 8-tenant planes "
            "(lane_health off vs armed) ticked tick-interleaved (the "
            "PR 15 A/B methodology: host drift cancels); the armed "
            "sentinel rides the SAME dispatch (zero extra dispatches), "
            "gated <5% per-tick overhead"),
        "sections_completed": [], "sections_skipped": {},
        "devices": "unknown", "provenance": None}
    _run_suite_guarded(result, _tenant_run)


def _tenant_run(result: dict) -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.config import TenancyConfig, micro_config
    from jax_mapping.models import fleet as FM
    from jax_mapping.sim import world as W
    from jax_mapping.tenancy.controlplane import TenantControlPlane

    cfg = micro_config()
    res = cfg.grid.resolution_m
    dev = jax.devices()[0]
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
        "grid": cfg.grid.size_cells, "patch": cfg.grid.patch_cells,
        "n_robots": cfg.fleet.n_robots, "n_missions": 32}

    world_np = W.empty_arena(cfg.grid.size_cells, res)
    world = jnp.asarray(world_np)
    key = jax.random.PRNGKey(0)
    n_missions = 32
    ticks = 20
    warm_ticks = 3
    result["mission_steps_per_point"] = ticks

    # --- megabatch: ONE control-plane step per tick -------------------
    # Throughput mode: capacities past the bit-exact ladder (the 16-
    # and 32-tenant points) are documented ulp-faithful, not bit-exact,
    # on XLA:CPU — megabatch.EXACT_BUCKETS is the contract boundary.
    ten_cfg = dataclasses.replace(cfg, tenancy=TenancyConfig(
        enabled=True, prewarm_on_admit=False, bit_exact_buckets=False))
    for T in result["tenant_counts"]:
        if _remaining() < 90.0:
            _skip_section(f"megabatch_{T}",
                          f"{_remaining():.0f}s left")
            continue
        cp = TenantControlPlane(ten_cfg, world_res_m=res)
        for m in range(T):
            cp.admit(f"m{m}", world_np, seed=m)
        cp.step(warm_ticks)                       # bucket compile + warm
        jax.block_until_ready(cp.live_batch().states.grid)
        t0 = time.perf_counter()
        for _ in range(ticks):
            cp.step(1)
            jax.block_until_ready(cp.live_batch().states.grid)
        dt = (time.perf_counter() - t0) / (ticks * T)
        result["megabatch_ms_per_mission_step"][str(T)] = \
            round(dt * 1e3, 4)
        result["megabatch_agg_steps_per_s"][str(T)] = round(1.0 / dt, 1)
        result["sections_completed"].append(f"megabatch_{T}")
        print(f"bench[tenant]: megabatch T={T}: "
              f"{dt * 1e3:.3f} ms/mission-step", file=sys.stderr,
              flush=True)
    from jax_mapping.tenancy.megabatch import megabatch_step
    try:
        result["bucket_variants_compiled"] = \
            int(megabatch_step._cache_size())
    except Exception:                       # noqa: BLE001 — telemetry
        pass

    # --- sentinel overhead: tick-interleaved armed/off A/B ------------
    # ISSUE 17 acceptance: the lane-health sentinel (health word fused
    # into the megabatch dispatch — zero extra dispatches) must cost
    # <5% per tick. The two planes alternate tick-for-tick (the PR 15
    # interleave methodology: host drift lands on both sides equally),
    # so the medians compare the same machine moment.
    if _remaining() > 60.0:
        armed_cfg = dataclasses.replace(cfg, tenancy=TenancyConfig(
            enabled=True, prewarm_on_admit=False,
            bit_exact_buckets=False, lane_health=True))
        T_s = 8
        planes = {}
        for label, c in (("off", ten_cfg), ("armed", armed_cfg)):
            p = TenantControlPlane(c, world_res_m=res)
            for m in range(T_s):
                p.admit(f"s{m}", world_np, seed=m)
            p.step(warm_ticks)
            jax.block_until_ready(p.live_batch().states.grid)
            planes[label] = p
        reps = 24
        times = {"off": [], "armed": []}
        for _ in range(reps):
            for label in ("off", "armed"):
                p = planes[label]
                t0 = time.perf_counter()
                p.step(1)
                jax.block_until_ready(p.live_batch().states.grid)
                times[label].append(time.perf_counter() - t0)
        off_ms = float(np.median(times["off"])) * 1e3
        armed_ms = float(np.median(times["armed"])) * 1e3
        frac = (armed_ms - off_ms) / off_ms if off_ms > 0 else None
        result["sentinel_overhead"] = {
            "tenant_count": T_s, "reps": reps,
            "off_ms_per_tick": round(off_ms, 4),
            "armed_ms_per_tick": round(armed_ms, 4),
            "overhead_frac": None if frac is None else round(frac, 4),
            "gate_frac": 0.05,
            "within_gate": None if frac is None else bool(frac < 0.05)}
        result["sections_completed"].append("sentinel_overhead")
        print(f"bench[tenant]: sentinel overhead: off {off_ms:.3f} ms "
              f"armed {armed_ms:.3f} ms/tick "
              f"({'n/a' if frac is None else f'{frac * 100:+.1f}%'})",
              file=sys.stderr, flush=True)
    else:
        _skip_section("sentinel_overhead", f"{_remaining():.0f}s left")

    # --- sequential floor: bare solo fleet_step per mission -----------
    if _remaining() > 60.0:
        states = [FM.init_fleet_state(cfg, jax.random.PRNGKey(m))
                  for m in range(n_missions)]
        s0, _ = FM.fleet_step(cfg, states[0], res, world)
        jax.block_until_ready(s0.grid)
        for w in range(warm_ticks):
            states = [FM.fleet_step(cfg, s, res, world)[0]
                      for s in states]
        jax.block_until_ready(states[-1].grid)
        t0 = time.perf_counter()
        for _ in range(ticks):
            nxt = []
            for s in states:
                s2, _ = FM.fleet_step(cfg, s, res, world)
                jax.block_until_ready(s2.grid)
                nxt.append(s2)
            states = nxt
        dt = (time.perf_counter() - t0) / (ticks * n_missions)
        result["sequential_dispatch_ms_per_mission_step"] = \
            round(dt * 1e3, 4)
        result["sections_completed"].append("sequential_dispatch")
        print(f"bench[tenant]: sequential dispatch: "
              f"{dt * 1e3:.3f} ms/mission-step", file=sys.stderr,
              flush=True)
    else:
        _skip_section("sequential_dispatch", f"{_remaining():.0f}s left")

    # --- sequential deployed form: one solo stack per mission ---------
    stack_ms = []
    for m in range(n_missions):
        if _remaining() < 45.0:
            _skip_section(f"sequential_stack_{m}",
                          f"{_remaining():.0f}s left")
            break
        st = launch_sim_stack(cfg, world_np, n_robots=1,
                              http_port=None, realtime=False, seed=m)
        try:
            st.brain.start_exploring()
            st.run_steps(warm_ticks)
            t0 = time.perf_counter()
            st.run_steps(ticks)
            stack_ms.append((time.perf_counter() - t0) / ticks * 1e3)
        finally:
            st.shutdown()
    if stack_ms:
        result["sequential_stack_ms_per_mission_step"] = \
            round(float(np.median(stack_ms)), 3)
        result["sequential_stack_missions_measured"] = len(stack_ms)
        result["sections_completed"].append("sequential_stack")
        print(f"bench[tenant]: sequential stack: "
              f"{np.median(stack_ms):.2f} ms/mission-step over "
              f"{len(stack_ms)} missions", file=sys.stderr, flush=True)

    mb32 = result["megabatch_ms_per_mission_step"].get("32")
    if mb32:
        if result["sequential_stack_ms_per_mission_step"]:
            result["speedup_32_vs_stack"] = round(
                result["sequential_stack_ms_per_mission_step"] / mb32, 2)
            result["value"] = result["speedup_32_vs_stack"]
        if result["sequential_dispatch_ms_per_mission_step"]:
            result["speedup_32_vs_dispatch"] = round(
                result["sequential_dispatch_ms_per_mission_step"] / mb32,
                2)


def _world_main() -> None:
    """`bench.py --suite world` — the bounded-memory world (ISSUE 18):
    steady-state mapper-tick overhead of the sliding-window machinery
    vs the fixed grid, plus the cost of one window shift (evict +
    roll + rehydrate).

    Two MapperNodes with IDENTICAL device grid geometry (the window
    size) tick the same interior drive tick-interleaved (the PR 15
    A/B methodology — host clock drift cancels): `fixed` is a plain
    256-cell grid, `windowed` is a 768-cell logical lattice served by
    a 4-tile (256-cell) device window, so the delta is exactly the
    per-tick window machinery (shift trigger check, prefetch poll,
    offset arithmetic) and not a grid-size difference. The interior
    drive never crosses the margin band, so no shift lands inside the
    timed span — that is the steady state the <5% gate reads.
    Shift cost is timed separately on a standalone WorldStore driving
    alternating ±1-tile shifts over a content-bearing window (each
    shift = extract leaving band + governor admit + one rolled
    dispatch + host-hit rehydrate scatters).

    CPU-pinned like the serving suite. Prints exactly ONE JSON line;
    `--out FILE` additionally writes it (the BENCH_WORLD_r* artifact)."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {
        "metric": "windowed_mapper_tick_overhead_frac", "suite": "world",
        "value": None,
        "fixed_tick_p50_ms": None, "windowed_tick_p50_ms": None,
        "overhead_frac": None, "gate_overhead_lt_5pct": None,
        "shift_p50_ms": None, "shift_reps": None,
        "ticks_measured": None, "warm_ticks": None,
        "window_tiles": 4, "logical_tiles": 12,
        "world_status": None,
        "methodology": (
            "tick-interleaved A/B wall time per MapperNode.tick() with "
            "a device barrier via the tick's own host sync (the PR 15 "
            "interleaving: host clock drift cancels); both mappers run "
            "the SAME 256-cell device grid — fixed = plain grid, "
            "windowed = 4-tile window of a 12-tile logical lattice — "
            "and the same zero-range interior drive (no shift inside "
            "the timed span), so overhead_frac is pure window "
            "machinery; gate_overhead_lt_5pct pins it under 5%. "
            "shift_p50_ms = standalone WorldStore alternating ±2-tile "
            "shifts over a content-bearing window, block_until_ready "
            "per shift — the content band leaves (governor admit) and "
            "re-enters (host-hit rehydrate scatter) every rep"),
        "sections_completed": [], "sections_skipped": {},
        "devices": "unknown", "provenance": None}
    _run_suite_guarded(result, _world_run)


def _world_run(result: dict) -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.messages import (
        Header, LaserScan, Odometry, Pose2D, Twist)
    from jax_mapping.config import tiny_config
    from jax_mapping.ops import grid as G
    from jax_mapping.world.store import WorldStore

    dev = jax.devices()[0]
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3]))}

    base = tiny_config(1)
    wcfg = base.replace(
        grid=dataclasses.replace(base.grid, size_cells=768),
        world=dataclasses.replace(base.world, windowed=True,
                                  window_tiles=4, margin_tiles=1))

    def make(cfg):
        bus = Bus()
        m = MapperNode(cfg, bus, n_robots=1)
        return m, bus.publisher("scan"), bus.publisher("odom")

    fixed, fscan, fodom = make(base)
    windowed, wscan, wodom = make(wcfg)
    n = base.scan.n_beams
    zeros = np.zeros(n, np.float32)

    def feed(scan_pub, odom_pub, t, x, y):
        odom_pub.publish(Odometry(
            header=Header(stamp=t, frame_id="odom"),
            pose=Pose2D(x, y, 0.0),
            twist=Twist(linear_x=0.0, angular_z=0.0)))
        scan_pub.publish(LaserScan(
            header=Header(stamp=t, frame_id="base_laser"),
            angle_increment=base.scan.angle_increment_rad,
            ranges=zeros))

    # Interior drive: a 0.3 m circle around the origin — deep inside
    # the 4-tile window's interior (the margin band starts at 3.2 m),
    # so the windowed mapper's shift trigger never fires mid-span.
    warm, ticks = 12, 60
    result["warm_ticks"], result["ticks_measured"] = warm, ticks
    fixed_ms, windowed_ms = [], []
    for k in range(warm + ticks):
        t = 0.1 * (k + 1)
        x = 0.3 * math.cos(0.2 * k)
        y = 0.3 * math.sin(0.2 * k)
        feed(fscan, fodom, t, x, y)
        t0 = time.perf_counter()
        fixed.tick()
        t1 = time.perf_counter()
        feed(wscan, wodom, t, x, y)
        t2 = time.perf_counter()
        windowed.tick()
        t3 = time.perf_counter()
        if k >= warm:
            fixed_ms.append((t1 - t0) * 1e3)
            windowed_ms.append((t3 - t2) * 1e3)
    fp50 = float(np.median(fixed_ms))
    wp50 = float(np.median(windowed_ms))
    result["fixed_tick_p50_ms"] = round(fp50, 3)
    result["windowed_tick_p50_ms"] = round(wp50, 3)
    overhead = wp50 / fp50 - 1.0
    result["overhead_frac"] = round(overhead, 4)
    result["value"] = result["overhead_frac"]
    result["gate_overhead_lt_5pct"] = bool(overhead < 0.05)
    ws = windowed.world_status()
    result["world_status"] = {k: ws[k] for k in
                              ("shifts", "evictions", "rehydrated_host",
                               "device_window_bytes")}
    result["sections_completed"].append("tick_overhead")
    print(f"bench[world]: fixed {fp50:.2f} ms, windowed {wp50:.2f} ms "
          f"-> overhead {overhead * 100:.1f}%",
          file=sys.stderr, flush=True)

    # Shift cost: content-bearing window, alternating ±1-tile column
    # shifts — every shift evicts a 4-tile band and rehydrates the
    # re-entering one from the host LRU.
    store = WorldStore(wcfg)
    win = G.empty_grid(store.cfg.grid)
    win = store.fuse_scan_global(
        win, jnp.full((base.scan.padded_beams,), 1.0, jnp.float32),
        jnp.zeros((3,), jnp.float32))
    # ±2-tile shifts so the content-bearing column actually LEAVES
    # (governor admit) and RE-ENTERS (host-hit rehydrate scatter) on
    # every rep — a ±1 shift only ever moves empty edge bands.
    win = jax.block_until_ready(store.shift(win, 0, 2))   # warm both
    win = jax.block_until_ready(store.shift(win, 0, -2))
    reps = 40
    shift_ms = []
    for k in range(reps):
        dc = 2 if k % 2 == 0 else -2
        t0 = time.perf_counter()
        win = jax.block_until_ready(store.shift(win, 0, dc))
        shift_ms.append((time.perf_counter() - t0) * 1e3)
    result["shift_p50_ms"] = round(float(np.median(shift_ms)), 3)
    result["shift_reps"] = reps
    result["sections_completed"].append("shift_cost")
    print(f"bench[world]: shift p50 {np.median(shift_ms):.2f} ms "
          f"({store.n_evictions} evictions, "
          f"{store.n_rehydrated_host} host rehydrates)",
          file=sys.stderr, flush=True)


def _run_suite_guarded(result: dict, run_fn) -> None:
    """ONE emit contract for the micro-suites (match, frontier):
    exactly one JSON line on stdout (+ `--out FILE` copy), printed by
    whichever fires first of normal completion, an exception, or the
    deadline watchdog — then a hard exit. Extracted so a fix to the
    contract cannot silently diverge between suites."""
    emitted = threading.Event()

    def emit(code: int = 0) -> None:
        if not emitted.is_set():
            emitted.set()
            _stamp_record(result, result.get("suite", "micro"),
                          _MAIN_METHODOLOGY)
            print(json.dumps(result), flush=True)
            if "--out" in sys.argv:
                i = sys.argv.index("--out")
                if i + 1 < len(sys.argv):
                    try:
                        with open(sys.argv[i + 1], "w") as f:
                            f.write(json.dumps(result) + "\n")
                    except OSError:
                        pass
        os._exit(code)

    watchdog = threading.Timer(max(_remaining(), 1.0), emit)
    watchdog.daemon = True
    watchdog.start()
    try:
        run_fn(result)
    except Exception:
        import traceback
        traceback.print_exc(file=sys.stderr)
    emit(0)


def _match_run(result: dict) -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import grid as G
    from jax_mapping.ops import pyramid as PYR
    from jax_mapping.ops import scan_match as M

    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3]))}

    # Same bench world as the main suite's matcher section: 256 scans
    # along a 0.4 m loop fused into the production 4096^2 grid.
    B = 256
    rng = np.random.default_rng(0)
    t = np.linspace(0, 2 * math.pi, B, endpoint=False)
    poses = np.stack([0.4 * np.cos(t), 0.4 * np.sin(t),
                      t + math.pi / 2], axis=1).astype(np.float32)
    ranges = rng.uniform(1.0, 10.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    ranges[rng.random((B, s.padded_beams)) < 0.05] = 0.0
    ranges_d = jax.device_put(jnp.asarray(ranges), dev)
    poses_d = jax.device_put(jnp.asarray(poses), dev)
    grid_arr = jax.jit(lambda: G.fuse_scans_window(
        g, s, G.empty_grid(g), ranges_d, poses_d))()
    jax.block_until_ready(grid_arr)
    # More repetitions than the main suite: this JSON line's headline is
    # a RATIO of two chains, and single-sample medians on a loaded CPU
    # host swing +-30% (measured) — enough to fake or hide the speedup.
    k1, k2, reps = (1, 3, 4) if on_cpu else (2, 10, 5)

    def match_chain_factory(m_cfg):
        def match_chain():
            def run_g(gr0, k):
                def body(_, p):
                    r = M.match(g, s, m_cfg, gr0, ranges_d[0], p)
                    return r.pose
                p = jax.lax.fori_loop(
                    0, k, body, jnp.zeros(3, jnp.float32) + 0.01)
                return p.sum()
            jitted = jax.jit(run_g)
            return lambda k: float(jitted(grid_arr, jnp.int32(k)))
        return match_chain

    for key, m_cfg in (
            ("pruned_p50_ms",
             dataclasses.replace(cfg.matcher, pruned=True)),
            ("exhaustive_p50_ms",
             dataclasses.replace(cfg.matcher, pruned=False))):
        if _remaining() < 60.0:
            print(f"bench[match]: skipping {key} "
                  f"({_remaining():.0f}s left)", file=sys.stderr,
                  flush=True)
            continue
        p50 = _chain_time(match_chain_factory(m_cfg), k1, k2, reps)
        result[key] = round(p50 * 1e3, 2)
        result["sections_completed"].append(key)
        print(f"bench[match]: {key} = {result[key]}",
              file=sys.stderr, flush=True)
    if result["exhaustive_p50_ms"] and result["pruned_p50_ms"]:
        result["speedup"] = round(
            result["exhaustive_p50_ms"] / result["pruned_p50_ms"], 2)

    # Steady-state cached path: repeated host-driven matches against an
    # unchanged map region (the relocalizer workload) — everything after
    # the first attempt must hit the pyramid cache.
    if _remaining() > 30.0:
        m_pr = dataclasses.replace(cfg.matcher, pruned=True)
        stride, n_steps = M.window_params(g, m_pr)
        lv = M.bnb_num_levels(m_pr, n_steps)
        guess = jnp.zeros(3, jnp.float32) + 0.01
        origin = G.patch_origin(g, guess[:2])
        cache = PYR.PyramidCache()
        revision = 7                      # frozen map: revision constant
        n_attempts = 8
        build_ms = None
        for a in range(n_attempts):
            t0 = time.perf_counter()
            levels = cache.get(
                ("bench", int(origin[0]), int(origin[1])), revision,
                lambda: PYR.build_match_pyramid(g, m_pr, lv, grid_arr,
                                                origin))
            jax.block_until_ready(levels[-1])
            if a == 0:
                build_ms = round((time.perf_counter() - t0) * 1e3, 2)
            res = M.match_with_pyramid(g, s, m_pr, lv, levels, origin,
                                       ranges_d[0], guess)
            jax.block_until_ready(res.pose)
        snap = cache.snapshot()
        result["pyramid_build_ms"] = build_ms
        result["pyramid_cache_hit_rate"] = round(snap["hit_rate"], 3)
        result["pyramid_cache"] = snap
        result["sections_completed"].append("pyramid_cache")


def _frontier_main() -> None:
    """`bench.py --suite frontier` — full-recompute vs incremental
    exploration-pipeline p50 at 64 robots on a production-shape (4096^2)
    mid-mission world, over steady-state and closure-storm dirty
    patterns, plus the publish-skip path and tile-cache hit rates.
    Prints exactly ONE JSON line; `--out FILE` additionally writes it
    (the BENCH_FRONTIER_r* artifact).

    CPU-pinned like the serving suite: the comparison is HOST-DRIVEN by
    construction — `publish_frontiers` is a host loop around device
    dispatches, and the incremental pipeline's cache decisions live on
    the host — so both sides are timed identically as per-publish wall
    time with a block_until_ready barrier. NOT comparable to the main
    suite's `frontier_p50_ms_64robots` chain numbers (the PR 5 gotcha:
    XLA:CPU runs convs ~10x slower inside fori_loop chains than
    standalone); the `methodology` field says so."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {
        "metric": "frontier_publish_p50_ms_64robots", "suite": "frontier",
        "full_p50_ms": None, "incremental_steady_p50_ms": None,
        "incremental_skip_p50_ms": None, "closure_storm_p50_ms": None,
        "speedup_steady": None, "speedup_storm": None,
        "tile_cache": None, "crop": None, "n_warm_starts": None,
        "methodology": (
            "host-driven per-publish wall time (block_until_ready "
            "barrier), BOTH paths — not comparable to the main suite's "
            "fori_loop chain p50s (PR 5 gotcha: CPU convs ~10x slower "
            "in-chain)"),
        "sections_completed": [], "sections_skipped": {},
        "devices": "unknown", "provenance": None}
    _run_suite_guarded(result, _frontier_run)


def _frontier_run(result: dict) -> None:
    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops.frontier_incremental import (
        IncrementalFrontierPipeline,
    )

    cfg = SlamConfig()
    g = cfg.grid
    fcfg = cfg.frontier
    tile = cfg.serving.tile_cells
    dev = jax.devices()[0]
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
        "grid": g.size_cells, "tile_cells": tile, "n_robots": 64}

    # Mid-mission world: a ~20 m observed disk (free space with wall
    # arcs) in the 205 m production grid — the regime the active-region
    # crop exists for — with 64 robots spread through the free interior.
    n = g.size_cells
    res = g.resolution_m
    rng = np.random.default_rng(0)
    lo = np.zeros((n, n), np.float32)
    cy, cx = n // 2, n // 2
    rad = int(20.0 / res)                                  # 400 cells
    yy, xx = np.ogrid[-rad:rad, -rad:rad]
    disk = (yy ** 2 + xx ** 2) < rad ** 2
    lo[cy - rad:cy + rad, cx - rad:cx + rad][disk] = -2.0
    for _ in range(24):                                    # wall segments
        r0 = rng.integers(cy - rad + 40, cy + rad - 80)
        c0 = rng.integers(cx - rad + 40, cx + rad - 80)
        if rng.random() < 0.5:
            lo[r0:r0 + 2, c0:c0 + int(rng.integers(40, 160))] = 2.0
        else:
            lo[r0:r0 + int(rng.integers(40, 160)), c0:c0 + 2] = 2.0
    ox, oy = g.origin_m
    ang = rng.uniform(0, 2 * np.pi, 64)
    rr = rng.uniform(1.0, 16.0, 64)
    poses = np.stack([ox + (cx + rr * np.cos(ang) / res) * res,
                      oy + (cy + rr * np.sin(ang) / res) * res,
                      rng.uniform(-3, 3, 64)], axis=1).astype(np.float32)
    nt = n // tile
    tile_rev = np.zeros((nt, nt), np.int64)
    rev = [0]

    def dirty_tiles(k: int) -> None:
        rev[0] += 1
        # Steady state: a couple of fusion patches near robots — mark
        # the 2x2 tile block around a random robot, like
        # _mark_dirty_patch's conservative extent.
        for _ in range(k):
            p = poses[rng.integers(64)]
            tr = int((p[1] - oy) / res) // tile
            tc = int((p[0] - ox) / res) // tile
            tile_rev[max(0, tr - 1):tr + 1, max(0, tc - 1):tc + 1] = rev[0]

    def jiggle() -> None:
        poses[:, :2] += rng.normal(0, 0.02, (64, 2)).astype(np.float32)

    lo_dev = jnp.asarray(lo)
    jax.block_until_ready(lo_dev)

    def timed(fn, reps, warmup=1):
        for _ in range(warmup):
            fn()
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) * 1e3

    # ---- incremental steady-state chain (priority 1) --------------------
    pipe = IncrementalFrontierPipeline(fcfg, g, tile)
    pipe.compute(lo_dev, poses, tile_rev, rev[0])          # cold build

    def steady_publish():
        dirty_tiles(2)
        jiggle()
        out = pipe.compute(lo_dev, poses, tile_rev, rev[0])
        assert out.recomputed

    if _remaining() > 60.0:
        p50 = timed(steady_publish, reps=15, warmup=3)
        result["incremental_steady_p50_ms"] = round(p50, 2)
        result["sections_completed"].append("incremental_steady")
        result["crop"] = list(pipe.last_crop)
        result["n_warm_starts"] = pipe.n_warm_starts
        print(f"bench[frontier]: steady = {result['incremental_steady_p50_ms']} ms",
              file=sys.stderr, flush=True)
    else:
        result["sections_skipped"]["incremental_steady"] = "deadline"

    # ---- full recompute (priority 2: the speedup denominator) -----------
    poses_dev = jnp.asarray(poses)

    def full_publish():
        fr = F.compute_frontiers(fcfg, g, lo_dev, poses_dev)
        jax.block_until_ready(fr.assignment)

    if _remaining() > 120.0:
        p50 = timed(full_publish, reps=5, warmup=1)
        result["full_p50_ms"] = round(p50, 2)
        result["sections_completed"].append("full")
        print(f"bench[frontier]: full = {result['full_p50_ms']} ms",
              file=sys.stderr, flush=True)
    else:
        result["sections_skipped"]["full"] = "deadline"
    if result["full_p50_ms"] and result["incremental_steady_p50_ms"]:
        result["speedup_steady"] = round(
            result["full_p50_ms"] / result["incremental_steady_p50_ms"], 2)

    # ---- publish skip (priority 3) --------------------------------------
    def skip_publish():
        out = pipe.compute(lo_dev, poses, tile_rev, rev[0])
        assert not out.recomputed

    if _remaining() > 30.0:
        pipe.compute(lo_dev, poses, tile_rev, rev[0])      # settle
        p50 = timed(skip_publish, reps=10, warmup=1)
        result["incremental_skip_p50_ms"] = round(p50, 3)
        result["sections_completed"].append("incremental_skip")
    else:
        result["sections_skipped"]["incremental_skip"] = "deadline"

    # ---- closure storm (priority 4: the adversarial pattern) ------------
    # A real closure re-fuse CHANGES content: alternate between two
    # device-resident grids differing by a wall, so every storm publish
    # re-coarsens everything AND the blocked-mask change forces a cold
    # field solve (revision bumps with identical content would be —
    # correctly — detected as no-ops and reuse the carried fields).
    lo2 = lo.copy()
    lo2[cy + 30:cy + 32, cx - 150:cx + 150] = 2.0
    lo2_dev = jnp.asarray(lo2)
    jax.block_until_ready(lo2_dev)

    def storm_publish():
        rev[0] += 1
        tile_rev[:] = rev[0]                               # all dirty
        jiggle()
        pipe.compute(lo2_dev if rev[0] % 2 else lo_dev, poses, tile_rev,
                     rev[0])

    if _remaining() > 90.0:
        p50 = timed(storm_publish, reps=4, warmup=1)
        result["closure_storm_p50_ms"] = round(p50, 2)
        result["sections_completed"].append("closure_storm")
        if result["full_p50_ms"]:
            result["speedup_storm"] = round(
                result["full_p50_ms"] / result["closure_storm_p50_ms"], 2)
    else:
        result["sections_skipped"]["closure_storm"] = "deadline"

    snap = pipe.status()
    result["tile_cache"] = {k: snap[k] for k in
                            ("cache_hits", "cache_misses",
                             "cache_hit_rate", "n_full_refreshes")}
    result["n_warm_starts"] = pipe.n_warm_starts
    result["n_field_reuses"] = pipe.n_field_reuses
    result["crop"] = list(pipe.last_crop) if pipe.last_crop else None


def _obs_main() -> None:
    """`bench.py --suite obs` — tracing overhead on the mapper-tick hot
    path (ISSUE 9 acceptance: `ObsConfig(enabled=True)` adds < 5% to
    mapper-tick p50). Two `launch_sim_stack` missions, same seed and
    world, obs off then on; every tick's duration is sampled from the
    `mapper.tick` StageTimer sum delta around `run_steps(1)` — the
    SAME measurement surface both ways (the stage wraps the tick body
    whether or not a Tracer exists). Plus span-primitive microbenches
    (emit / on_publish cost). Prints exactly ONE JSON line; `--out
    FILE` additionally writes it (the BENCH_OBS_r* artifact).

    CPU-pinned like the serving/frontier suites: the number is a HOST
    overhead ratio by construction — tracing is host-side bookkeeping
    (blake2b ids + a locked deque append), nothing lands on the
    device, so the denominator backend only scales both sides."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {
        "metric": "mapper_tick_p50_obs_overhead_pct", "suite": "obs",
        "tick_p50_ms_obs_off": None, "tick_p50_ms_obs_on": None,
        "overhead_pct": None, "overhead_p90_pct": None,
        "spans_per_tick": None, "span_emit_us": None,
        "publish_derive_us": None,
        # ISSUE 10: the dispatch profiler's own mapper-tick overhead
        # (obs tracing + devprof both armed, vs the obs-off baseline)
        # — must stay under the same 5% gate.
        "tick_p50_ms_devprof_on": None, "devprof_overhead_pct": None,
        "devprof_dispatches_per_tick": None,
        # ISSUE 15: the freshness tier's own mapper-tick overhead —
        # tracing + pipeline-ledger waypoint stamps + per-tick SLO
        # evaluation over three live objectives, vs the same obs-off
        # baseline. Same 5% gate (BENCH_OBS_r03).
        "tick_p50_ms_slo_on": None, "slo_overhead_pct": None,
        "pipeline_stamps_per_tick": None, "slo_evaluations": None,
        "methodology": (
            "per-tick wall time from the mapper.tick StageTimer sum "
            "delta around run_steps(1), same-seed same-world missions "
            "obs off vs on, host-driven on virtual CPU (tracing is "
            "host-side bookkeeping; the device backend scales both "
            "sides equally)"),
        "sections_completed": [], "sections_skipped": {},
        "devices": "unknown", "provenance": None}
    _run_suite_guarded(result, _obs_run)


def _obs_run(result: dict) -> None:
    import jax

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.config import ObsConfig, tiny_config
    from jax_mapping.sim import world as W
    from jax_mapping.utils import global_metrics

    dev = jax.devices()[0]
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
        "n_robots": 2, "warmup_steps": 12, "measured_steps": 72}

    cfg0 = tiny_config()
    world, _ = W.rooms_with_doors(96, cfg0.grid.resolution_m, seed=1)
    WARM, REPS = 12, 72

    def drive(obs_on, devprof_on=False):
        from jax_mapping.config import DevProfConfig
        cfg = cfg0.replace(obs=ObsConfig(
            enabled=obs_on,
            devprof=DevProfConfig(enabled=devprof_on)))
        st = launch_sim_stack(cfg, world, n_robots=2, realtime=False,
                              seed=0)
        st.brain.start_exploring()
        st.run_steps(WARM)                       # jit compiles settle
        ticks_ms = []
        for _ in range(REPS):
            before = global_metrics.stages.snapshot().get(
                "mapper.tick", {"sum_ms": 0.0})["sum_ms"]
            st.run_steps(1)
            after = global_metrics.stages.snapshot()["mapper.tick"]
            ticks_ms.append(after["sum_ms"] - before)
        n_spans = st.tracer.last_seq() if st.tracer is not None else 0
        n_disp = (sum(v["count"] for v in st.devprof.snapshot().values())
                  if st.devprof is not None else 0)
        st.shutdown()
        return np.asarray(ticks_ms), n_spans, n_disp

    off_ms, _, _ = drive(False)
    result["sections_completed"].append("obs_off")
    on_ms, n_spans, _ = drive(True)
    result["sections_completed"].append("obs_on")
    p50_off = float(np.percentile(off_ms, 50))
    p50_on = float(np.percentile(on_ms, 50))
    result["tick_p50_ms_obs_off"] = round(p50_off, 3)
    result["tick_p50_ms_obs_on"] = round(p50_on, 3)
    result["overhead_pct"] = round((p50_on / p50_off - 1.0) * 100, 2)
    result["overhead_p90_pct"] = round(
        (float(np.percentile(on_ms, 90))
         / float(np.percentile(off_ms, 90)) - 1.0) * 100, 2)
    result["spans_per_tick"] = round(n_spans / (WARM + REPS), 1)

    # ISSUE 10: tracing AND the dispatch profiler armed — the full
    # observability stack's tick overhead against the same baseline.
    dev_ms, _, n_disp = drive(True, devprof_on=True)
    result["sections_completed"].append("devprof_on")
    p50_dev = float(np.percentile(dev_ms, 50))
    result["tick_p50_ms_devprof_on"] = round(p50_dev, 3)
    result["devprof_overhead_pct"] = round(
        (p50_dev / p50_off - 1.0) * 100, 2)
    result["devprof_dispatches_per_tick"] = round(
        n_disp / (WARM + REPS), 1)

    # ISSUE 15: the freshness tier armed — tracing + pipeline ledger
    # waypoint stamps + per-tick SLO evaluation over three live
    # objectives, against a TICK-INTERLEAVED obs-off baseline (<5%
    # gate). This builder's throughput drifts several percent over
    # seconds (the --regress lesson), so sequential off-then-on drives
    # — even alternating whole-drive rounds — read weather as overhead
    # in either direction; here BOTH stacks are live at once and the
    # measured ticks alternate one-for-one, so any drift lands on both
    # sides of every adjacent pair. The two stacks share jit caches
    # (identical shapes: the freshness tier adds no jitted code — the
    # claim under test).
    def _interleaved_slo():
        from jax_mapping.config import DevProfConfig, SloObjective
        slo_objs = (
            SloObjective(name="fresh",
                         metric="scan_to_served_p99_ms",
                         threshold=1e9, max_silent_ticks=10 ** 6),
            SloObjective(name="stale", metric="tile_staleness_revs",
                         threshold=1e9),
            SloObjective(name="deadline", metric="tick_deadline_ms",
                         threshold=1e9),
        )
        cfgs = {
            "off": cfg0.replace(obs=ObsConfig(
                enabled=False, devprof=DevProfConfig(enabled=False))),
            "slo": cfg0.replace(obs=ObsConfig(
                enabled=True, slo=slo_objs,
                devprof=DevProfConfig(enabled=False))),
        }
        stacks = {k: launch_sim_stack(c, world, n_robots=2,
                                      realtime=False, seed=0)
                  for k, c in cfgs.items()}
        samples = {k: [] for k in stacks}
        for st in stacks.values():
            st.brain.start_exploring()
            st.run_steps(WARM)
        for _ in range(REPS):
            for k, st in stacks.items():
                before = global_metrics.stages.snapshot().get(
                    "mapper.tick", {"sum_ms": 0.0})["sum_ms"]
                st.run_steps(1)
                after = global_metrics.stages.snapshot()[
                    "mapper.tick"]
                samples[k].append(after["sum_ms"] - before)
        n_stamps = stacks["slo"].pipeline.n_stamps
        n_evals = stacks["slo"].slo.status()["n_evaluations"]
        for st in stacks.values():
            st.shutdown()
        return (np.asarray(samples["off"]), np.asarray(samples["slo"]),
                n_stamps, n_evals)

    off_i, slo_i, n_stamps, n_evals = _interleaved_slo()
    result["sections_completed"].append("slo_on")
    p50_off_i = float(np.percentile(off_i, 50))
    p50_slo_i = float(np.percentile(slo_i, 50))
    result["tick_p50_ms_slo_off_interleaved"] = round(p50_off_i, 3)
    result["tick_p50_ms_slo_on"] = round(p50_slo_i, 3)
    result["slo_overhead_pct"] = round(
        (p50_slo_i / p50_off_i - 1.0) * 100, 2)
    result["pipeline_stamps_per_tick"] = round(
        n_stamps / (WARM + REPS), 1)
    result["slo_evaluations"] = n_evals

    # Span-primitive microbenches: the per-event cost tracing adds to
    # any instrumented path (blake2b id + locked ring append).
    from jax_mapping.obs import Tracer
    tr = Tracer(seed=0)
    N = 20000
    t0 = time.perf_counter()
    for k in range(N):
        tr.emit("bench.span", key=k)
    result["span_emit_us"] = round(
        (time.perf_counter() - t0) / N * 1e6, 3)
    t0 = time.perf_counter()
    for _ in range(N):
        tr.on_publish("/bench")
    result["publish_derive_us"] = round(
        (time.perf_counter() - t0) / N * 1e6, 3)
    result["sections_completed"].append("primitives")


def _fuse_main() -> None:
    """`bench.py --suite fuse` — the ISSUE 11 gate: the classic
    classify->fold->full-grid-hash dispatch chain vs the fused
    single-dispatch path (`ops/fuse_kernel.fuse_scans_window_touched`)
    at the production 4096^2 / 640-patch config, host-driven per call
    with a device barrier (NOT the fori_loop chain form — the PR 5
    CPU-conv gotcha: XLA:CPU runs chained convs ~10x slower in-loop, so
    chain p50s are not comparable to these). Also records the static
    XLA cost-ledger bytes/FLOPs for both variants and the dispatch
    profiler's per-call dispatch counts. Prints exactly ONE JSON line;
    `--out FILE` additionally writes it (the BENCH_FUSE_r* artifact).

    CPU-pinned like the serving/frontier suites: the headline is a
    same-host RATIO (both variants share the grid, scans and
    methodology), and the tier-1 acceptance names the CPU streaming
    engine; the Pallas fused kernel's numbers belong to an on-chip
    BENCH_LOCAL_r* run."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        from jax_mapping.utils.backend_guard import scrubbed_cpu_env
        os.execvpe(sys.executable, [sys.executable] + sys.argv,
                   scrubbed_cpu_env(extra_env={
                       "JAX_PLATFORMS": "cpu",
                       "JAX_MAPPING_BENCH_DEADLINE_S":
                           str(max(60.0, _remaining()))}))
    result = {
        "metric": "fused_fusion_window_chain_speedup", "suite": "fuse",
        "classic_chain_p50_ms": None, "fused_p50_ms": None,
        "speedup": None,
        "classic_fuse_p50_ms": None, "full_hash_p50_ms": None,
        "scatter_classic_p50_ms": None, "scatter_fused_p50_ms": None,
        "scatter_speedup": None,
        "classic_bytes_accessed": None, "fused_bytes_accessed": None,
        "bytes_ratio": None, "classic_flops": None, "fused_flops": None,
        "scatter_classic_bytes": None, "scatter_fused_bytes": None,
        "classic_dispatches_per_call": None,
        "fused_dispatches_per_call": None,
        "window_scans": None, "scatter_scans": None,
        "methodology": (
            "host-driven per-call wall time with a block_until_ready "
            "barrier (NOT fori_loop chains — the PR 5 CPU-conv gotcha); "
            "classic chain = fuse_scans_window(fused_fusion=False) + "
            "to_gray + full-grid tile_hashes as separate dispatches "
            "(the pre-fused per-tick serving flow), fused = ONE "
            "fuse_scans_window_touched dispatch whose bounded "
            "touched-tile hash rides inside; bytes/FLOPs from "
            "lowered.compile().cost_analysis(), dispatch counts from "
            "the PR 10 DispatchProfiler"),
        "sections_completed": [], "sections_skipped": {},
        "devices": "unknown", "provenance": None}
    _run_suite_guarded(result, _fuse_run)


def _fuse_run(result: dict) -> None:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import fuse_kernel as FK
    from jax_mapping.ops import grid as G

    cfg = SlamConfig()
    s = cfg.scan
    gc = dataclasses.replace(cfg.grid, fused_fusion=False)
    gf = dataclasses.replace(cfg.grid, fused_fusion=True)
    tile = cfg.serving.tile_cells
    dev = jax.devices()[0]
    result["devices"] = f"{len(jax.devices())}x {dev.platform}"
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    result["provenance"] = {
        "cpu_count": os.cpu_count(), "loadavg_1m": load1,
        "jax": jax.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
        "grid": gc.size_cells, "patch": gc.patch_cells,
        "tile_cells": tile}

    # Workload: one mapper tick's window (fleet.batch_scans consecutive
    # scans on a 0.4 m loop — inside the shared-patch contract) into a
    # mid-mission grid, plus a scattered batch big enough to cross the
    # streaming sub-chunk boundary (the memory-bounding regime).
    WB = cfg.fleet.batch_scans
    SB = 256
    result["window_scans"] = WB
    result["scatter_scans"] = SB
    rng = np.random.default_rng(0)
    t = np.linspace(0, 2 * math.pi, SB, endpoint=False)
    poses = np.stack([0.4 * np.cos(t), 0.4 * np.sin(t),
                      t + math.pi / 2], axis=1).astype(np.float32)
    ranges = rng.uniform(1.0, 10.0, (SB, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    ranges[rng.random((SB, s.padded_beams)) < 0.05] = 0.0
    rd, pd = jnp.asarray(ranges), jnp.asarray(poses)
    rw, pw = rd[:WB], pd[:WB]
    grid0 = G.fuse_scans_window(gc, s, G.empty_grid(gc), rd, pd)
    jax.block_until_ready(grid0)

    def timed(fn, reps=5, warm=2):
        for _ in range(warm):
            fn()
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) * 1e3

    # ---- the chains ------------------------------------------------------
    def classic_chain():
        g1 = G.fuse_scans_window(gc, s, grid0, rw, pw)
        gray = G.to_gray(gc, g1)
        h = G.tile_hashes(gray, tile)
        jax.block_until_ready(h)
        return g1

    def fused_call():
        g2, rc, h = FK.fuse_scans_window_touched(gf, s, tile, grid0,
                                                 rw, pw)
        jax.block_until_ready(h)
        return g2

    # Same map out of both paths (last-ulp window reassociation aside).
    np.testing.assert_allclose(np.asarray(classic_chain()),
                               np.asarray(fused_call()), atol=1e-5)

    if _remaining() > 120.0:
        result["classic_chain_p50_ms"] = round(timed(classic_chain), 2)
        result["fused_p50_ms"] = round(timed(fused_call), 2)
        result["speedup"] = round(result["classic_chain_p50_ms"]
                                  / result["fused_p50_ms"], 3)
        result["sections_completed"].append("window_chain")
        print(f"bench[fuse]: classic {result['classic_chain_p50_ms']} ms "
              f"vs fused {result['fused_p50_ms']} ms "
              f"(x{result['speedup']})", file=sys.stderr, flush=True)
        # Stage budget: the fuse alone and the full-grid hash alone.
        result["classic_fuse_p50_ms"] = round(timed(
            lambda: jax.block_until_ready(
                G.fuse_scans_window(gc, s, grid0, rw, pw))), 2)
        result["full_hash_p50_ms"] = round(timed(
            lambda: jax.block_until_ready(
                G.tile_hashes(G.to_gray(gc, grid0), tile))), 2)
    else:
        result["sections_skipped"]["window_chain"] = "deadline"

    # ---- scattered streaming fold vs classic materialise-then-fold ------
    # The scatter trade is MEMORY, not wall clock, on CPU: the stream
    # bounds transient deltas at _STREAM_CHUNK x 1.6 MB (vs the classic
    # chunk's 420 MB) for a measured ~5-19% interleave cost — record
    # both sides (time AND cost-ledger bytes) so the trade is on the
    # trajectory, not asserted.
    if _remaining() > 120.0:
        result["scatter_classic_p50_ms"] = round(timed(
            lambda: jax.block_until_ready(
                G.fuse_scans(gc, s, grid0, rd, pd)), reps=3, warm=1), 2)
        result["scatter_fused_p50_ms"] = round(timed(
            lambda: jax.block_until_ready(
                G.fuse_scans(gf, s, grid0, rd, pd)), reps=3, warm=1), 2)
        result["scatter_speedup"] = round(
            result["scatter_classic_p50_ms"]
            / result["scatter_fused_p50_ms"], 3)
        result["sections_completed"].append("scatter")
    else:
        result["sections_skipped"]["scatter"] = "deadline"

    # ---- static cost ledger: bytes/FLOPs per variant --------------------
    def cost(lowerable, *args):
        ca = lowerable.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None, None
        return ca.get("bytes accessed"), ca.get("flops")

    if _remaining() > 60.0:
        gray0 = G.to_gray(gc, grid0)
        pieces = [cost(G.fuse_scans_window, gc, s, grid0, rw, pw),
                  cost(G.to_gray, gc, grid0),
                  cost(G.tile_hashes, gray0, tile)]
        # Bytes and FLOPs gate independently: a backend reporting
        # 'bytes accessed' without 'flops' must not TypeError away the
        # whole section (and the later dispatch accounting with it).
        if all(b is not None for b, _ in pieces):
            result["classic_bytes_accessed"] = sum(b for b, _ in pieces)
        if all(f is not None for _, f in pieces):
            result["classic_flops"] = sum(f for _, f in pieces)
        fb, ff = cost(FK.fuse_scans_window_touched, gf, s, tile, grid0,
                      rw, pw)
        result["fused_bytes_accessed"] = fb
        result["fused_flops"] = ff
        if fb and result["classic_bytes_accessed"] is not None:
            result["bytes_ratio"] = round(
                result["classic_bytes_accessed"] / fb, 3)
        result["scatter_classic_bytes"], _ = cost(G.fuse_scans, gc, s,
                                                  grid0, rd, pd)
        result["scatter_fused_bytes"], _ = cost(G.fuse_scans, gf, s,
                                                grid0, rd, pd)
        result["sections_completed"].append("cost_ledger")
        print(f"bench[fuse]: bytes classic "
              f"{result['classic_bytes_accessed']} vs fused {fb} "
              f"(x{result['bytes_ratio']})", file=sys.stderr, flush=True)
    else:
        result["sections_skipped"]["cost_ledger"] = "deadline"

    # ---- dispatch accounting (PR 10 profiler) ---------------------------
    if _remaining() > 30.0:
        from jax_mapping.config import DevProfConfig
        from jax_mapping.obs.devprof import DispatchProfiler
        prof = DispatchProfiler(DevProfConfig(enabled=True))
        prof.install()
        try:
            classic_chain()
            n_classic = sum(v["count"]
                            for v in prof.snapshot().values())
            before = n_classic
            fused_call()
            n_fused = sum(v["count"]
                          for v in prof.snapshot().values()) - before
        finally:
            prof.uninstall()
        result["classic_dispatches_per_call"] = n_classic
        result["fused_dispatches_per_call"] = n_fused
        result["sections_completed"].append("dispatches")
    else:
        result["sections_skipped"]["dispatches"] = "deadline"


def _costfield_xla_fallback() -> None:
    """Flip the frontier cost-field engine to its XLA twin and drop EVERY
    cached trace (the env var is read at trace time, but outer jits —
    frontier.compute_frontiers in particular — cache closed-call jaxprs
    with the Pallas call already embedded; clearing only cost_fields'
    cache left the round-2 retry re-tracing the same rejected kernel)."""
    import jax
    os.environ["JAX_MAPPING_COSTFIELD_XLA"] = "1"
    os.environ["JAX_MAPPING_FRONTIER_XLA"] = "1"
    jax.clear_caches()
    # The caller decides whether to relabel costfield_path: a retry of the
    # euclid section (cost fields never ran) must not mislabel the engine
    # the already-recorded obstacle-aware number was measured on.


def _is_tunnel_failure(e: Exception) -> bool:
    """Is the remote TPU compile TRANSPORT dead (vs. a rejectable
    kernel)? Kernel rejections also arrive via the remote helper (HTTP
    500 + Mosaic details) and MUST keep taking the XLA-twin fallback, so
    only connection-level markers count. Timeout strings ('timed out',
    'Deadline Exceeded') deliberately do NOT count (ADVICE r3): a slow
    Mosaic compile or a watchdog-expired kernel is a rejectable-kernel
    case — it must take the in-process XLA-twin fallback, not re-exec
    the whole bench onto virtual CPU."""
    msg = str(e)
    return any(m in msg for m in (
        "Connection refused", "Failed to connect", "Connection reset",
        "Couldn't connect"))


def _chain_time(make_fn, k1: int, k2: int, reps: int,
                label: str = None) -> float:
    """Median per-iteration seconds for a chained-loop fn factory.

    make_fn() must return f(k) that runs a k-iteration device chain and
    fetches a scalar forcing it. The chain length is a TRACED argument
    (lax.fori_loop with a dynamic trip count) so both lengths share ONE
    compilation — the per-section compile cost through the remote TPU
    compile tunnel dominated the bench wall clock when every section
    compiled two chain lengths.

    k2 GROWS until the marginal signal t(k2)-t(k1) clears the timing
    noise (same executable — the trip count is traced, growth is free),
    and the chosen basis is recorded in provenance.

    This + the chain loop-dependence guards explain the BENCH_r03/r04
    17x fuse "anomaly" (VERDICT r4 weak #1). Two compounding artifacts,
    neither hardware, neither the measured code: (1) the old fuse chain
    was loop-INVARIANT in its classify inputs, so XLA hoisted the whole
    classification out of the fori_loop and the chain timed only the
    640^2 patch apply — a ~1.3 ms marginal against a ~1.6 s chain
    constant (grid materialise + fetch) on a 1-core CPU box; (2) at the
    old fixed k2=3 that 2.6 ms signal sat inside scheduler jitter, so
    noise flipped the formula between marginal (r4: 7509 scans/s, idle
    repro: 17943) and the whole-chain fallback that charges the
    constant to throughput (r3: 431.6, loaded repro: 437.4) — measured
    on one box minutes apart. With the dependence guard the honest CPU
    classify is ~1.2 s/window (~210 scans/s); the TPU headline must be
    re-measured on-chip (the r3 kernel-stage budget, measured through
    the already-guarded kernel_chain, puts the kernel alone at
    5.9 ms/window, so ~43 k scans/s remains the expected order).
    """
    f = make_fn()
    f(k1)  # compile + warm (same executable serves both lengths)
    f(k2)

    def med(k):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f(k)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), float(np.max(ts) - np.min(ts))

    t1, spread1 = med(k1)
    while True:
        t2, _ = med(k2)
        signal = t2 - t1
        if signal > max(4.0 * spread1, 0.05 * t1):
            break
        grown = k2 * 3
        # Growth budget: one more round costs ~reps * t(grown). Project
        # conservatively by scaling the WHOLE measured chain time (the
        # constant inflates the estimate) — projecting from `signal`
        # would under-estimate exactly when growth triggers (noise can
        # make signal <= 0) and approve rounds that blow the deadline.
        if grown > 100 or \
                reps * max(t1, t2) * grown / k2 \
                > max(_remaining() - 30.0, 0.0):
            break
        k2 = grown
    basis = "marginal" if t2 > t1 else "whole-chain"
    if label is not None:
        prov = _RESULT.get("provenance") or {}
        prov.setdefault("timing", {})[label] = {
            "t1_s": round(t1, 4), "t2_s": round(t2, 4),
            "k1": k1, "k2": k2, "basis": basis}
        _RESULT["provenance"] = prov
    if t2 > t1:
        return (t2 - t1) / (k2 - k1)
    return t2 / k2


def _run() -> None:
    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    dev = jax.devices()[0]
    n_dev = len(jax.devices())
    on_cpu = dev.platform == "cpu"
    cpu_fallback = os.environ.get("_JAX_MAPPING_BENCH_CPU_FALLBACK") == "1"
    _RESULT["devices"] = f"{n_dev}x {dev.platform}" + (
        " (tpu tunnel unreachable, virtual-cpu fallback)" if cpu_fallback
        else "")
    # Provenance (VERDICT r4 weak #1): BENCH_r04's CPU fuse number was 17x
    # BENCH_r03's with identical measurement AND measured code (diffed) —
    # builder repro on the r5 image got 437.4 scans/s, agreeing with r3's
    # 431.6, so r4's 7509.6 came from the driver host, not the repo (a
    # beefier or idler machine parallelising the window classify; the
    # conv-bound frontier/match sections barely moved). These fields make
    # round-over-round artifacts comparable: environment variance is only
    # diagnosable if the JSON says what hardware produced the number.
    try:
        load1 = round(os.getloadavg()[0], 1)
    except OSError:
        load1 = None
    import jaxlib
    _RESULT["provenance"] = {
        "cpu_count": os.cpu_count(),
        "loadavg_1m": load1,
        "python": ".".join(map(str, sys.version_info[:3])),
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", None),
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }
    if cpu_fallback:
        # Virtual-CPU numbers say nothing about the TPU framework; point
        # the reader at the NEWEST builder-measured HARDWARE record —
        # skipping CPU-fallback records, which would make the pointer a
        # self-referential loop when the newest local artifact is itself
        # a wedged-tunnel fallback.
        import glob
        import json as _json
        recs = sorted(glob.glob(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "BENCH_LOCAL_r*.json")))
        # The key is ALWAYS present on fallback runs (prior artifacts all
        # carry it; a consumer indexing it must not KeyError) — None
        # records "no hardware run exists anywhere", not a missing field.
        _RESULT["tpu_numbers_recorded_in"] = None
        for rec in reversed(recs):
            try:
                with open(rec) as f:
                    devices = _json.load(f).get("devices", "")
            except (OSError, ValueError):
                continue
            if "tpu" in devices and "unreachable" not in devices:
                _RESULT["tpu_numbers_recorded_in"] = os.path.basename(rec)
                break

    # ---- engine choice: probe the Pallas kernel once on tiny shapes ------
    # A Mosaic/toolchain rejection must cost seconds, not the round: fall
    # back to the parity-tested XLA paths in-process (fresh traces read the
    # env var; nothing compiled yet has baked the choice in).
    if G._use_pallas():
        try:
            from jax_mapping.config import tiny_config
            from jax_mapping.ops import sensor_kernel as SK
            tc = tiny_config()
            tg, ts_ = tc.grid, tc.scan
            r0 = jnp.zeros((2, ts_.padded_beams), jnp.float32)
            p0 = jnp.zeros((2, 3), jnp.float32)
            o0 = jnp.zeros(2, jnp.int32)
            jax.block_until_ready(SK.window_delta(tg, ts_, r0, p0, o0))
        except Exception as e:
            print(f"bench: pallas probe failed ({type(e).__name__}: {e}); "
                  "using XLA fallback paths", file=sys.stderr, flush=True)
            os.environ["JAX_MAPPING_NO_PALLAS"] = "1"
        # The cost-field relaxation kernel is probed separately: a Mosaic
        # rejection there must only flip the frontier engine to its XLA
        # twin, not take down the (independent) fusion kernel. Probes are
        # shape-dependent evidence only — the frontier section below has
        # its own production-shape fallback.
        if os.environ.get("JAX_MAPPING_NO_PALLAS") != "1":
            try:
                from jax_mapping.ops import costfield as CF
                blk = jnp.zeros((64, 64), bool)
                rc = jnp.zeros((2, 2), jnp.int32)
                jax.block_until_ready(CF.cost_fields(blk, rc, 2, 2))
            except Exception as e:
                print(f"bench: costfield pallas probe failed "
                      f"({type(e).__name__}: {e}); frontier uses the XLA "
                      "twin", file=sys.stderr, flush=True)
                _costfield_xla_fallback()
                _RESULT["costfield_path"] = "xla-fallback"
    _RESULT["path"] = ("pallas" if G._use_pallas()
                       else ("xla-fallback"
                             if os.environ.get("JAX_MAPPING_NO_PALLAS") == "1"
                             else "xla"))
    if _RESULT["costfield_path"] is None:
        from jax_mapping.ops import costfield as CF
        _RESULT["costfield_path"] = ("pallas" if CF._use_pallas() else "xla")

    # ---- workload: B scans along a realistic local trajectory -----------
    # One robot's temporal scan window: consecutive LD06 rotations while the
    # robot drives a 0.4 m-radius loop (a Thymio at cruise covers < 1 m in
    # 256 scan rotations, server main.py:60). The radius must stay inside
    # the shared patch's WORST-CASE slack of (P/2 - align/2 - max_range)
    # cells = 0.8 m — patch-origin alignment can eat up to align_cols/2
    # cells of the nominal 4 m margin, and a dead-centre mean pose lands
    # exactly on that worst case.
    B = 256
    rng = np.random.default_rng(0)
    t = np.linspace(0, 2 * math.pi, B, endpoint=False)
    poses = np.stack([
        0.4 * np.cos(t), 0.4 * np.sin(t), t + math.pi / 2
    ], axis=1).astype(np.float32)
    # Plausible LD06 returns: walls 1-10 m away, 5% dropouts (zeros).
    ranges = rng.uniform(1.0, 10.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    drop = rng.random((B, s.padded_beams)) < 0.05
    ranges[drop] = 0.0

    ranges_d = jax.device_put(jnp.asarray(ranges), dev)
    poses_d = jax.device_put(jnp.asarray(poses), dev)
    # The window path silently drops updates outside the shared patch; fail
    # loudly if a future workload edit breaks the window contract instead
    # of inflating the metric with partially-dropped work.
    from jax_mapping.ops import sensor_kernel as SK
    origin = G.patch_origin(g, poses_d[:, :2].mean(0))
    assert bool(SK.window_fits(g, poses_d, origin)), \
        "bench trajectory violates the shared-patch window contract"

    # Chain lengths / repetitions sized for the platform (CPU fallback runs
    # the same program ~2 orders slower; keep it inside the deadline).
    k1, k2, reps = (1, 3, 2) if on_cpu else (2, 10, 5)

    def fuse_chain():
        def run(k):
            def body(_, gr):
                # Thread the grid into the CLASSIFY inputs (gr[0,0]*0.0
                # is numerically zero — the grid is clamp-bounded — but
                # not provably so to XLA): the window delta doesn't
                # otherwise depend on the loop state, and XLA hoists the
                # whole classification out of the fori_loop, leaving a
                # chain that times only the 640^2 patch apply (~1.3 ms vs
                # the honest ~1.2 s/window classify on a 1-core CPU,
                # measured with this guard at k2=9) — the invariant-code-
                # motion hazard the frontier/kernel chains already guard.
                return G.fuse_scans_window(g, s, gr, ranges_d,
                                           poses_d + gr[0, 0] * 0.0)
            gr = jax.lax.fori_loop(0, k, body, G.empty_grid(g))
            return gr.sum()
        jitted = jax.jit(run)
        return lambda k: float(jitted(jnp.int32(k)))

    target = 50_000.0 * n_dev / 8.0
    try:
        dt = _chain_time(fuse_chain, k1, k2, reps, label="fuse")
        scans_per_sec = B / dt
        _RESULT["value"] = round(scans_per_sec, 1)
        _RESULT["vs_baseline"] = round(scans_per_sec / target, 3)
        _RESULT["sections_completed"].append("fuse")
        # Stage budget on stderr (VERDICT r2 #3): the kernel alone vs the
        # full fuse (kernel + grid read-modify-write + chain glue).
        # Pallas path only: calling the kernel directly off-TPU would run
        # interpret mode, which is pathologically slow at this shape. Own
        # try: a stage-budget failure must not re-enter the fuse fallback
        # and overwrite the recorded Pallas numbers.
        if _RESULT["path"] == "pallas" and _remaining() > 90.0:
            try:
                def kernel_chain():
                    def run(k):
                        def body(_, d):
                            d2 = SK.window_delta(g, s, ranges_d,
                                                 poses_d + d * 0.0, origin)
                            return d2[:1, :1].reshape(())[None, None]
                        d = jax.lax.fori_loop(
                            0, k, body, jnp.zeros((1, 1), jnp.float32))
                        return d.sum()
                    jitted = jax.jit(run)
                    return lambda k: float(jitted(jnp.int32(k)))
                kdt = _chain_time(kernel_chain, k1, k2, reps,
                                  label="fuse_kernel")
                print(f"bench: fuse stage budget — window kernel "
                      f"{kdt * 1e3:.2f} ms, full fuse {dt * 1e3:.2f} ms "
                      f"({B} scans/window)", file=sys.stderr, flush=True)
            except Exception:
                import traceback
                traceback.print_exc(file=sys.stderr)
    except Exception as e:
        import traceback
        traceback.print_exc(file=sys.stderr)
        if _is_tunnel_failure(e) and not cpu_fallback:
            # Half-up tunnel: backend init answered the probe but every
            # compile dies in the remote helper. No engine swap can help —
            # limping on would fail all six sections and emit an all-null
            # JSON. Take the virtual-CPU path instead (same re-exec the
            # init probe uses; deadline already capped by _scrub_cpu_env).
            print("bench: remote TPU compile tunnel failing; re-exec onto "
                  "virtual CPU", file=sys.stderr, flush=True)
            os.execvpe(sys.executable, [sys.executable] + sys.argv,
                       _scrub_cpu_env())
        if G._use_pallas():
            # In-process engine fallback: re-trace with XLA paths.
            print("bench: pallas fuse failed, re-tracing with XLA fallback",
                  file=sys.stderr, flush=True)
            os.environ["JAX_MAPPING_NO_PALLAS"] = "1"
            _RESULT["path"] = "xla-fallback"
            dt = _chain_time(fuse_chain, k1, k2, reps,
                             label="fuse_fallback")
            scans_per_sec = B / dt
            _RESULT["value"] = round(scans_per_sec, 1)
            _RESULT["vs_baseline"] = round(scans_per_sec / target, 3)
            _RESULT["sections_completed"].append("fuse")
        else:
            raise

    # ---- section scheduling (r06, budget-aware) -------------------------
    # BENCH_r05 starved fleet_tick_* and plan outright: the fixed order
    # ran both frontier modes + voxel before them, and on a slow host the
    # budget was gone. Sections now run in PRIORITY order — one data
    # point per subsystem before any subsystem's second data point — and
    # every skip is recorded in `sections_skipped` with its reason.
    import dataclasses
    robot_poses = jax.device_put(jnp.asarray(
        np.stack([rng.uniform(-50, 50, 64), rng.uniform(-50, 50, 64),
                  rng.uniform(-3, 3, 64)], 1).astype(np.float32)), dev)
    grid_arr = jax.jit(lambda: G.fuse_scans_window(
        g, s, G.empty_grid(g), ranges_d, poses_d))()
    jax.block_until_ready(grid_arr)

    def frontier_chain_factory(fcfg):
        def frontier_chain():
            # grid rides as an ARGUMENT: closure capture makes it an XLA
            # constant and const-folding the coarsen masks costs ~40 s of
            # compile per chain (measured) against the bench deadline.
            def run_g(gr0, k):
                def body(_, carry):
                    gr, acc = carry
                    fr = F.compute_frontiers(fcfg, g, gr, robot_poses)
                    dep = fr.costs.sum() * 0.0    # data-dep chains iterations
                    return (gr + dep, acc + fr.sizes.sum())
                _, acc = jax.lax.fori_loop(0, k, body,
                                           (gr0, jnp.int32(0)))
                return acc
            jitted = jax.jit(run_g)
            return lambda k: float(jitted(grid_arr, jnp.int32(k)))
        return frontier_chain

    def run_frontier(key: str, aware: bool) -> None:
        fcfg = dataclasses.replace(cfg.frontier, obstacle_aware=aware)
        try:
            p50 = _chain_time(frontier_chain_factory(fcfg), k1, k2, reps)
            _RESULT[key] = round(p50 * 1e3, 2)
            _RESULT["sections_completed"].append(key)
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)
            # Retry on the XLA twins iff a frontier-side Pallas engine was
            # actually active (cost fields, or the label-prop kernel at
            # this config's clustering grid size) — a pure-XLA failure
            # would only repeat itself and burn the watchdog budget.
            from jax_mapping.ops import frontier as FK
            cluster_n = (g.size_cells // fcfg.downsample
                         // fcfg.cluster_downsample)
            lp_active = FK._use_pallas_labels(cluster_n)
            # The label-prop kernel runs in BOTH cost modes; the cost-field
            # kernel only in the obstacle-aware one.
            if lp_active or (aware
                             and _RESULT.get("costfield_path") == "pallas"):
                # Production-shape Mosaic/VMEM failures get past the tiny
                # probe; retry the headline frontier metric on the XLA twin
                # rather than dropping it.
                print("bench: frontier failed at production shape; "
                      "retrying with the frontier XLA twins",
                      file=sys.stderr, flush=True)
                _costfield_xla_fallback()
                if aware:
                    _RESULT["costfield_path"] = "xla-fallback"
                try:
                    p50 = _chain_time(frontier_chain_factory(fcfg), k1, k2,
                                      reps)
                    _RESULT[key] = round(p50 * 1e3, 2)
                    _RESULT["sections_completed"].append(key)
                except Exception:
                    traceback.print_exc(file=sys.stderr)

    # ---- matcher + full slam_step at production config ------------------
    # The per-key-scan costs: what slam_toolbox pays at 10 Hz
    # (slam_config.yaml:24-38). Chained through the refined pose / carried
    # state so iterations are data-dependent. `match_p50_ms` measures the
    # product-default matcher (branch-and-bound since r06; `--suite
    # match` carries the exhaustive-vs-pruned comparison).
    from jax_mapping.models import slam as SM
    from jax_mapping.ops import scan_match as M

    def run_match() -> None:
        def match_chain():
            def run_g(gr0, k):
                def body(_, p):
                    r = M.match(g, s, cfg.matcher, gr0, ranges_d[0], p)
                    return r.pose
                p = jax.lax.fori_loop(
                    0, k, body, jnp.zeros(3, jnp.float32) + 0.01)
                return p.sum()
            jitted = jax.jit(run_g)
            return lambda k: float(jitted(grid_arr, jnp.int32(k)))
        try:
            p50 = _chain_time(match_chain, k1, k2, reps, label="match")
            _RESULT["match_p50_ms"] = round(p50 * 1e3, 2)
            _RESULT["sections_completed"].append("match")
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)

    def run_slam_step() -> None:
        state0 = SM.init_state(cfg)
        # Wheel speed sized so EVERY iteration passes the 0.1 m key-scan
        # gate (0.12 m per 0.1 s step): the metric is the per-KEY-scan
        # cost — match + fuse + graph — not the cheap sub-gate branch a
        # slow robot would mostly take.
        wl = jnp.float32(4000.0)
        wr = jnp.float32(4000.0)
        dts = jnp.float32(0.1)

        def slam_chain():
            def run_g(st0, k):
                def body(i, st):
                    st2, _diag = SM.slam_step(cfg, st, ranges_d[0], wl, wr,
                                              dts)
                    return st2
                st = jax.lax.fori_loop(0, k, body, st0)
                return st.pose.sum() + st.grid.sum()
            jitted = jax.jit(run_g)
            return lambda k: float(jitted(state0, jnp.int32(k)))
        try:
            p50 = _chain_time(slam_chain, k1, k2, reps,
                              label="slam_step")
            _RESULT["slam_step_p50_ms"] = round(p50 * 1e3, 2)
            _RESULT["sections_completed"].append("slam_step")
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)

    # ---- full closed-loop fleet tick, 8 AND 64 robots, production grid --
    # sense (simulated LD06 raycast against a ground-truth world) ->
    # frontier assignment -> policy -> kinematics -> odometry -> gated
    # match/fuse/graph. The reference's 10 Hz single-robot loop
    # (server/.../main.py:60,83-200), batched over BASELINE.json config 4's
    # fleet — both ends of its N=8-64 span (the 64-robot number was the
    # round-3 verdict's missing data point: 64x the 3-pass conv matcher is
    # the likeliest budget-killer and must be on the record). Includes the
    # sim's own raycasts — a real deployment replaces those with robots'
    # actual scans.
    from jax_mapping.models import fleet as FL
    from jax_mapping.sim import world as W
    fleet_world = {}                    # built lazily on first timed config

    def run_fleet(n_robots: int, key: str) -> None:
        if on_cpu and n_robots > 8:
            # The 64-robot production tick exists to answer a TPU budget
            # question; on the virtual-CPU fallback it would only eat the
            # watchdog deadline the remaining sections need.
            _skip_section(key, "cpu fallback")
            return
        if "w" not in fleet_world:
            world = W.plank_course(g.size_cells, g.resolution_m,
                                   n_planks=40, seed=0)
            fleet_world["w"] = jax.device_put(jnp.asarray(world), dev)
        world_d = fleet_world["w"]
        cfg_n = dataclasses.replace(
            cfg, fleet=dataclasses.replace(cfg.fleet, n_robots=n_robots))
        fstate0 = FL.init_fleet_state(cfg_n, jax.random.PRNGKey(0))

        def fleet_chain():
            def run_g(st, k):
                def body(_, s2):
                    s3, _diag = FL.fleet_step(cfg_n, s2, g.resolution_m,
                                              world_d)
                    return s3
                out = jax.lax.fori_loop(0, k, body, st)
                return out.grid.sum() + out.est_poses.sum()
            jitted = jax.jit(run_g)
            return lambda k: float(jitted(fstate0, jnp.int32(k)))
        try:
            p50 = _chain_time(fleet_chain, 1, 3, min(reps, 3),
                              label=f"fleet_tick_{n_robots}")
            _RESULT[key] = round(p50 * 1e3, 2)
            _RESULT["sections_completed"].append(f"fleet_tick_{n_robots}")
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)

    # ---- 3D voxel fusion throughput (BASELINE configs[4]) ---------------
    # Depth images fused into the production (64, 1024, 1024) 0.05 m
    # log-odds voxel grid. Target (VERDICT r4): >= 640 images/s = 64
    # robots x the reference's 10 Hz sensor cadence (server main.py:60).
    # Inputs are REAL renders of the plank-course world through the sim
    # depth cam (VERDICT r4 weak #6: uniform speckle never exercised the
    # frustum/occlusion-heavy geometry) — rendered OUTSIDE the timed
    # region; the renderer is not part of the fusion cost a deployment
    # pays. `voxel_path` records the engine fuse_depths dispatched to
    # (the Pallas kernel on TPU, ops/voxel_kernel.py; XLA elsewhere).
    def run_voxel() -> None:
        from jax_mapping.ops import voxel as VX
        from jax_mapping.sim import depthcam as DCam
        from jax_mapping.sim import world as SimW
        vox, cam = cfg.voxel, cfg.depthcam
        VB = 32
        vworld = SimW.plank_course(512, g.resolution_m, n_planks=10,
                                   seed=7)
        t2_ = np.linspace(0, 2 * math.pi, VB, endpoint=False)
        vposes = np.stack([3.0 * np.cos(t2_), 3.0 * np.sin(t2_),
                           t2_ + math.pi / 2], axis=1).astype(np.float32)
        vdepths_d = jax.device_put(DCam.render_depths(
            cam, jnp.asarray(vworld), g.resolution_m, 200,
            jnp.asarray(vposes)), dev)
        vposes_d = jax.device_put(jnp.asarray(vposes), dev)
        _RESULT["voxel_path"] = ("pallas" if VX._use_pallas(vox, cam)
                                 else "xla")

        def voxel_chain():
            def run(k):
                def body(_, gr):
                    # Loop-dependence guard — see fuse_chain.
                    return VX.fuse_depths(vox, cam, gr, vdepths_d,
                                          vposes_d + gr[0, 0, 0] * 0.0)
                gr = jax.lax.fori_loop(0, k, body,
                                       VX.empty_voxel_grid(vox))
                return gr.sum()
            jitted = jax.jit(run)
            return lambda k: float(jitted(jnp.int32(k)))
        try:
            dt = _chain_time(voxel_chain, 1, 3, min(reps, 3),
                             label="voxel")
            _RESULT["voxel_images_per_sec"] = round(VB / dt, 1)
            _RESULT["sections_completed"].append("voxel")
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)

        # Shared-patch window path (one robot's consecutive frames —
        # voxel_kernel.window_delta replaces the B-step fold with one
        # aligned read-modify-write). Kernel engine only: interpret-mode
        # pallas off-TPU is pathologically slow at production shapes.
        if not VX._use_pallas(vox, cam):
            _skip_section("voxel_window", "no pallas voxel engine")
        elif _remaining() < 60.0:
            _skip_section("voxel_window",
                          f"{_remaining():.0f}s left < 60s floor")
        else:
            from jax_mapping.ops import voxel_kernel as VKK
            wt = np.linspace(0, 0.5, VB).astype(np.float32)
            wposes_d = jax.device_put(jnp.asarray(np.stack(
                [0.2 * np.cos(wt * 2 * np.pi), 0.2 * np.sin(wt * 2 * np.pi),
                 wt], axis=1).astype(np.float32)), dev)
            worigin = VX.patch_origin(vox, wposes_d[:, :2].mean(0))
            assert bool(VKK.window_fits(vox, wposes_d, worigin)), \
                "bench window trajectory violates the shared-patch contract"

            def vwindow_chain():
                def run(k):
                    def body(_, gr):
                        # Loop-dependence guard — see fuse_chain.
                        d = VKK.window_delta(vox, cam, vdepths_d,
                                             wposes_d + gr[0, 0, 0] * 0.0,
                                             worigin)
                        return VX.apply_patch(vox, gr, d, worigin)
                    gr = jax.lax.fori_loop(0, k, body,
                                           VX.empty_voxel_grid(vox))
                    return gr.sum()
                jitted = jax.jit(run)
                return lambda k: float(jitted(jnp.int32(k)))
            try:
                dt = _chain_time(vwindow_chain, 1, 3, min(reps, 3),
                                 label="voxel_window")
                _RESULT["voxel_window_images_per_sec"] = round(VB / dt, 1)
                _RESULT["sections_completed"].append("voxel_window")
            except Exception:
                import traceback
                traceback.print_exc(file=sys.stderr)

    # ---- global planner: replan latency at production scale --------------
    # The round-5 navigation capability (ops/planner.py): goal-seeded
    # obstacle-aware cost-to-go over the coarse 1024^2 field + greedy
    # descent, one jit. Budget: PlannerConfig.period_s (= 1 s) per replan;
    # the p50 must sit far under it for the planner to ride the mapper's
    # cadence without stealing the hot path's device time.
    def run_plan() -> None:
        from jax_mapping.ops import planner as PL
        pcfg = cfg.planner
        nlo = g.size_cells
        plan_lo = np.full((nlo, nlo), -1.0, np.float32)
        prng = np.random.default_rng(5)
        for _ in range(40):                  # random axis-aligned walls
            wr = int(prng.integers(0, nlo - 200))
            wc = int(prng.integers(0, nlo - 200))
            if prng.random() < 0.5:
                plan_lo[wr:wr + 200, wc:wc + 4] = 3.0
            else:
                plan_lo[wr:wr + 4, wc:wc + 200] = 3.0
        plan_lo_d = jax.device_put(jnp.asarray(plan_lo), dev)
        ox, oy = g.origin_m
        span = nlo * g.resolution_m
        start_xy = jnp.asarray([ox + 0.25 * span, oy + 0.25 * span],
                               jnp.float32)
        goal_xy = jnp.asarray([ox + 0.65 * span, oy + 0.65 * span],
                              jnp.float32)

        def plan_chain():
            def run(k):
                def body(_, s):
                    # Loop-dependence guard (see fuse_chain): the carry IS
                    # the iteration's waypoint sum and feeds the next
                    # start via `* 0.0` — value-neutral, but XLA cannot
                    # fold x*0 (NaN/Inf) so the plans stay serialized.
                    r = PL.plan_to_goal(pcfg, cfg.frontier, g, plan_lo_d,
                                        goal_xy, start_xy + s * 0.0)
                    return r.waypoint_xy.sum()
                return jax.lax.fori_loop(0, k, body, jnp.float32(0))
            jitted = jax.jit(run)
            return lambda k: float(jitted(jnp.int32(k)))
        try:
            dt = _chain_time(plan_chain, 1, 3, min(reps, 3), label="plan")
            _RESULT["plan_p50_ms"] = round(dt * 1000.0, 2)
            _RESULT["sections_completed"].append("plan")
        except Exception:
            import traceback
            traceback.print_exc(file=sys.stderr)

    # ---- the schedule ----------------------------------------------------
    # Priority = one data point per subsystem before any subsystem's
    # second: hot-path metrics (match, slam_step), then the per-subsystem
    # first points (frontier obstacle-aware, fleet@8, plan, voxel), then
    # the second points (frontier euclid, fleet@64; voxel_window rides
    # inside run_voxel with its own floor). Floors are the historical
    # worst-case compile+measure costs; a section that does not fit is
    # recorded in `sections_skipped`, never silently dropped.
    sections = (
        ("match", 90.0, run_match),
        ("slam_step", 90.0, run_slam_step),
        ("frontier_p50_ms_64robots", 60.0,
         lambda: run_frontier("frontier_p50_ms_64robots", True)),
        ("fleet_tick_8", 150.0,
         lambda: run_fleet(8, "fleet_tick_p50_ms_8robots")),
        ("plan", 150.0, run_plan),
        ("voxel", 90.0, run_voxel),
        ("frontier_euclid_p50_ms_64robots", 30.0,
         lambda: run_frontier("frontier_euclid_p50_ms_64robots", False)),
        ("fleet_tick_64", 150.0,
         lambda: run_fleet(64, "fleet_tick_p50_ms_64robots")),
    )
    for key, min_budget, fn in sections:
        if _remaining() < min_budget:
            _skip_section(
                key, f"{_remaining():.0f}s left < {min_budget:.0f}s floor")
            continue
        fn()


if __name__ == "__main__":
    main()
