"""Benchmark: LiDAR scan fusion throughput into the full-size 4096^2 grid.

Headline metric per BASELINE.md: >= 50,000 scans/sec fused into a 4096^2
0.05 m log-odds grid on a v5e-8. This runs on whatever devices are visible
(the driver provides one real chip) and pro-rates the baseline target by
device count: vs_baseline = scans_per_sec / (50_000 * n_devices / 8).

Also measures frontier recompute latency at 64 robots (target < 5 ms p50);
the reported figure is the median-across-repetitions of per-iteration
device time (see _chain_time), reported as `frontier_p50_ms_64robots`.

Methodology — honest device-side timing. On the tunneled TPU platform used
here, `jax.block_until_ready` returns before execution finishes and any
host-synchronising fetch pays a large fixed round-trip (~70 ms measured).
So each workload is timed as a `lax.fori_loop` chain of K data-dependent
iterations inside ONE jit, synchronised by fetching a scalar, at two chain
lengths K1 < K2; per-iteration device time = (t(K2) - t(K1)) / (K2 - K1),
which cancels the fixed dispatch + fetch overhead exactly. This is the
device-kernel latency/throughput the BASELINE targets describe (on-pod
there is no tunnel RTT).

Prints exactly ONE JSON line.
"""

import json
import math
import sys
import time

import numpy as np


def _chain_time(make_jit, k1: int = 2, k2: int = 10, reps: int = 5) -> float:
    """Median per-iteration seconds for a chained-loop jit factory.

    make_jit(k) must return a nullary jitted fn whose result forces the
    whole k-iteration chain (returns a scalar; we fetch it with float()).
    The estimate is (median t(k2) - median t(k1)) / (k2 - k1). If host
    jitter inverts the difference, the chain lengths are doubled once (a
    larger spread drowns the jitter); if it still inverts, fall back to
    median t(k2) / k2 — an upper bound that *includes* the fixed dispatch
    overhead, i.e. errs against us rather than fabricating a fast result.
    """
    def med(f):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f())
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    for mult in (1, 4):
        ka, kb = k1 * mult, k2 * mult
        f1, f2 = make_jit(ka), make_jit(kb)
        float(f1())  # compile + warm
        float(f2())
        t1, t2 = med(f1), med(f2)
        if t2 > t1:
            return (t2 - t1) / (kb - ka)
    return t2 / kb


def main() -> None:
    try:
        _run()
    except Exception:
        # A Mosaic/toolchain failure of the Pallas engine must not cost the
        # round its benchmark record: re-exec once with the parity-tested
        # XLA fallback paths (grid._use_pallas) and report that honestly in
        # the JSON's "path" field. Fresh process, because jitted branches
        # bake the engine choice at trace time. Only meaningful where the
        # Pallas engine was actually in play (TPU backend).
        import os
        import traceback
        from jax_mapping.ops.grid import _use_pallas
        if not _use_pallas():
            raise
        traceback.print_exc(file=sys.stderr)
        print("bench: pallas path failed, re-running with XLA fallback",
              file=sys.stderr)
        env = dict(os.environ, JAX_MAPPING_NO_PALLAS="1")
        os.execvpe(sys.executable, [sys.executable] + sys.argv, env)


def _run() -> None:
    import os

    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    dev = jax.devices()[0]
    n_dev = len(jax.devices())

    # ---- workload: B scans along a realistic local trajectory -----------
    # One robot's temporal scan window: consecutive LD06 rotations while the
    # robot drives a ~3 m loop (the shared-patch fast path's contract; the
    # reference robot moves ~1 cm per scan rotation, server main.py:60).
    B = 256
    rng = np.random.default_rng(0)
    t = np.linspace(0, 2 * math.pi, B, endpoint=False)
    poses = np.stack([
        1.5 * np.cos(t), 1.5 * np.sin(t), t + math.pi / 2
    ], axis=1).astype(np.float32)
    # Plausible LD06 returns: walls 1-10 m away, 5% dropouts (zeros).
    ranges = rng.uniform(1.0, 10.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    drop = rng.random((B, s.padded_beams)) < 0.05
    ranges[drop] = 0.0

    ranges_d = jax.device_put(jnp.asarray(ranges), dev)
    poses_d = jax.device_put(jnp.asarray(poses), dev)
    # The window path silently drops updates outside the shared patch; fail
    # loudly if a future workload edit breaks the window contract instead
    # of inflating the metric with partially-dropped work.
    from jax_mapping.ops import sensor_kernel as SK
    origin = G.patch_origin(g, poses_d[:, :2].mean(0))
    assert bool(SK.window_fits(g, poses_d, origin)), \
        "bench trajectory violates the shared-patch window contract"

    def fuse_chain(k):
        def run():
            def body(_, gr):
                return G.fuse_scans_window(g, s, gr, ranges_d, poses_d)
            gr = jax.lax.fori_loop(0, k, body, G.empty_grid(g))
            return gr.sum()
        return jax.jit(run)

    dt = _chain_time(fuse_chain)
    scans_per_sec = B / dt

    # ---- frontier recompute p50 at 64 robots ---------------------------
    import dataclasses
    fcfg = dataclasses.replace(cfg.frontier, obstacle_aware=False)
    robot_poses = jax.device_put(jnp.asarray(
        np.stack([rng.uniform(-50, 50, 64), rng.uniform(-50, 50, 64),
                  rng.uniform(-3, 3, 64)], 1).astype(np.float32)), dev)
    grid_arr = jax.jit(lambda: G.fuse_scans_window(
        g, s, G.empty_grid(g), ranges_d, poses_d))()

    def frontier_chain(k):
        def run():
            def body(_, carry):
                gr, acc = carry
                fr = F.compute_frontiers(fcfg, g, gr, robot_poses)
                dep = fr.costs.sum() * 0.0    # data-dep chains iterations
                return gr + dep, acc + fr.sizes.sum()
            _, acc = jax.lax.fori_loop(0, k, body, (grid_arr, jnp.int32(0)))
            return acc
        return jax.jit(run)

    frontier_p50_ms = _chain_time(frontier_chain) * 1e3

    target = 50_000.0 * n_dev / 8.0
    print(json.dumps({
        "metric": "lidar_scan_fusion_throughput",
        "value": round(scans_per_sec, 1),
        "unit": "scans/sec into 4096^2 0.05m grid",
        "vs_baseline": round(scans_per_sec / target, 3),
        "devices": f"{n_dev}x {dev.platform}",
        "frontier_p50_ms_64robots": round(frontier_p50_ms, 2),
        "path": ("pallas" if G._use_pallas()
                 else ("xla-fallback"
                       if os.environ.get("JAX_MAPPING_NO_PALLAS") == "1"
                       else "xla")),
    }))


if __name__ == "__main__":
    sys.exit(main())
