"""Benchmark: LiDAR scan fusion throughput into the full-size 4096^2 grid.

Headline metric per BASELINE.md: >= 50,000 scans/sec fused into a 4096^2
0.05 m log-odds grid on a v5e-8. This runs on whatever devices are visible
(the driver provides one real chip) and pro-rates the baseline target by
device count: vs_baseline = scans_per_sec / (50_000 * n_devices / 8).

Also measures p50 frontier recompute latency at 64 robots (target < 5 ms)
and reports it inside the JSON line as an extra field.

Prints exactly ONE JSON line.
"""

import json
import math
import sys
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from jax_mapping.config import SlamConfig
    from jax_mapping.ops import frontier as F
    from jax_mapping.ops import grid as G

    cfg = SlamConfig()
    g, s = cfg.grid, cfg.scan
    dev = jax.devices()[0]
    n_dev = len(jax.devices())

    # ---- workload: B scans along a loop through a synthetic interior ----
    B = 256
    rng = np.random.default_rng(0)
    t = np.linspace(0, 2 * math.pi, B, endpoint=False)
    poses = np.stack([
        30.0 * np.cos(t), 30.0 * np.sin(t), t + math.pi / 2
    ], axis=1).astype(np.float32)
    # Plausible LD06 returns: walls 1-10 m away, 5% dropouts (zeros).
    ranges = rng.uniform(1.0, 10.0, (B, s.padded_beams)).astype(np.float32)
    ranges[:, s.n_beams:] = 0.0
    drop = rng.random((B, s.padded_beams)) < 0.05
    ranges[drop] = 0.0

    grid = jax.device_put(G.empty_grid(g), dev)
    ranges_d = jax.device_put(jnp.asarray(ranges), dev)
    poses_d = jax.device_put(jnp.asarray(poses), dev)

    fuse = lambda gr: G.fuse_scans(g, s, gr, ranges_d, poses_d)
    grid = fuse(grid)                      # compile + warm
    jax.block_until_ready(grid)

    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        grid = fuse(grid)
    jax.block_until_ready(grid)
    dt = (time.perf_counter() - t0) / iters
    scans_per_sec = B / dt

    # ---- frontier recompute p50 at 64 robots ---------------------------
    import dataclasses
    fcfg = dataclasses.replace(cfg.frontier, obstacle_aware=False)
    robot_poses = jax.device_put(jnp.asarray(
        np.stack([rng.uniform(-50, 50, 64), rng.uniform(-50, 50, 64),
                  rng.uniform(-3, 3, 64)], 1).astype(np.float32)), dev)
    fr = F.compute_frontiers(fcfg, g, grid, robot_poses)   # compile
    jax.block_until_ready(fr)
    lat = []
    for _ in range(11):
        t0 = time.perf_counter()
        fr = F.compute_frontiers(fcfg, g, grid, robot_poses)
        jax.block_until_ready(fr)
        lat.append(time.perf_counter() - t0)
    frontier_p50_ms = float(np.median(lat) * 1e3)

    target = 50_000.0 * n_dev / 8.0
    print(json.dumps({
        "metric": "lidar_scan_fusion_throughput",
        "value": round(scans_per_sec, 1),
        "unit": "scans/sec into 4096^2 0.05m grid",
        "vs_baseline": round(scans_per_sec / target, 3),
        "devices": f"{n_dev}x {dev.platform}",
        "frontier_p50_ms_64robots": round(frontier_p50_ms, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
